"""RADOS client: computes placement itself and talks straight to primaries.

Role-equivalent of librados + Objecter (reference src/osdc/Objecter.cc:2257
op_submit / _calc_target): fetch the OSDMap from the mon, map
object -> PG -> primary locally, send the op to the primary, and on failure
refetch the map and resend (the Objecter's retry-across-epochs behavior,
idempotent by reqid)."""

from __future__ import annotations

import asyncio
import errno
import uuid
from typing import Dict, List, Optional, Tuple

from ceph_tpu.rados.messenger import BufferList, Messenger
from ceph_tpu.rados.monclient import MonTargets
from ceph_tpu.rados.types import (
    MAuthTicket,
    MAuthTicketReply,
    MConfigGet,
    MNotifyAck,
    MWatchNotify,
    MConfigReply,
    MConfigSet,
    MCreatePool,
    MCreatePoolReply,
    MDeletePool,
    MGetMap,
    MMapReply,
    MPoolSet,
    MSetUpmap,
    MMarkDown,
    MOSDOp,
    MOSDOpReply,
    MSnapOp,
    MSnapOpReply,
    OSDMap,
    SNAP_SEP,
)


class RadosError(Exception):
    """Client-visible failure.  ``code`` is the negative errno from the
    reply (0 when the failure had no typed reply, e.g. transport errors),
    so services can branch on errno instead of message text."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


# reply codes that are ANSWERS, not failures: the primary executed the op
# and the result is "no" — retrying would turn every expected miss into a
# multi-second epoch-barrier stall (reference: definitive errno from
# PrimaryLogPG are returned to the caller, not retried by the Objecter)
_DEFINITIVE_CODES = frozenset((
    -errno.ENOENT, -errno.EOPNOTSUPP, -errno.EINVAL, -errno.EPERM,
    -errno.EBADMSG, -errno.ENXIO, -errno.EEXIST, -errno.ERANGE,
    # compound-op asserts: cmpxattr mismatch / missing xattr are verdicts
    # about object state, not transients (reference rados_exec rvals)
    -errno.ECANCELED, -errno.ENODATA,
))
# -ESTALE (not primary): the placement this op was computed on is WRONG —
# re-target only after fencing past our own epoch (a newer map exists or
# is imminent; recomputing on the stale one re-picks the same primary).
# -EAGAIN (degraded below min_size / shards unavailable): the cure is a
# MAP CHANGE (failure detection marking the dead member down, recovery
# re-seating shards) — fence past our epoch and wait for it, or the
# retries burn out inside the detection grace window.
# -EBUSY (sub-write ack shortfall): the write partially landed and a
# plain resend usually completes it — retry promptly WITHOUT an epoch
# wait (one dropped ack on a healthy cluster must not pay a multi-second
# epoch poll).


class RadosClient:
    def __init__(self, mon_addr, conf: Optional[dict] = None):
        # one mon addr or a monmap list; RPCs rotate on mon failure
        self.mons = MonTargets(mon_addr)
        self.conf = conf or {}
        self.op_timeout = self.conf.get("client_op_timeout", 10.0)
        self.messenger = Messenger("client", self.conf, entity_type="client")
        self.osdmap: Optional[OSDMap] = None
        self._replies: Dict[str, asyncio.Future] = {}
        self._mon_fut: Optional[asyncio.Future] = None
        self._mon_tid: str = ""
        # serialize mon RPCs: _mon_fut is a single slot, and concurrent ops
        # retrying through refresh_map() must not clobber each other
        self._mon_lock = asyncio.Lock()
        # (pool, oid) -> callback(oid, payload) for watch/notify
        self._watches: Dict = {}
        # linger state (reference Objecter::linger_watch, Objecter.cc:598):
        # (pool, oid) -> primary the watch was registered with; on a map
        # change that moves the primary, the watch re-registers itself
        self._watch_primaries: Dict[Tuple[int, int], Optional[int]] = {}
        self._relinger_task: Optional[asyncio.Task] = None
        self._linger_poll_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self.messenger.dispatcher = self._dispatch
        # rx batches resolve their reply futures in one pass (and the
        # batch's frames get ONE piggybacked ack instead of one each —
        # an op-reply flood from a busy primary costs a single flush)
        self.messenger.group_dispatcher = self._dispatch_group
        await self.messenger.bind()
        if self.conf.get("auth_cephx", False):
            await self._fetch_ticket()

    async def _fetch_ticket(self) -> None:
        """cephx-lite: obtain a service ticket over a BOOTSTRAP-
        authenticated mon connection; OSD dials present it instead of
        the cluster secret.  The mon refuses to mint tickets over
        ticket-authenticated conns (self-renewal would void the TTL), so
        drop any held ticket and live mon conns first — the re-dial then
        proves the cluster secret."""
        if self.messenger.ticket is not None:
            self.messenger.ticket = None
            self.messenger.session_key = None
            for addr in list(self.mons.addrs):
                await self.messenger.disconnect(addr)
        reply = await self._mon_rpc(
            MAuthTicket(entity="client", entity_type="client"))
        if getattr(reply, "denied", False):
            raise PermissionError("mon refused to mint a client ticket")
        self.messenger.ticket = bytes.fromhex(reply.ticket)
        self.messenger.session_key = bytes.fromhex(reply.session_key)

    async def stop(self) -> None:
        for t in (self._linger_poll_task, self._relinger_task):
            if t is not None and not t.done():
                t.cancel()
        await self.messenger.shutdown()

    async def _dispatch_group(self, conn, msgs) -> None:
        """A whole rx batch (already-buffered frames): replies resolve
        their futures back-to-back; per-message work is future-set cheap,
        so order-preserving serial dispatch is the right partition here —
        the win is the messenger's single cumulative ack for the batch.
        Per-message isolation matches the serve loop's: one raising
        message (e.g. a watch-ack dial failing) must not drop — and
        still ack — the rest of the batch."""
        for msg in msgs:
            try:
                await self._dispatch(conn, msg)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                import traceback

                traceback.print_exc()

    async def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, MWatchNotify):
            # ack FIRST (delivery receipt — divergence from notify2, which
            # acks after processing): a slow callback must not look like a
            # dead watcher and get pruned; then run the callback
            try:
                await self.messenger.send(
                    tuple(msg.reply_to),
                    MNotifyAck(notify_id=msg.notify_id,
                               watcher=self.messenger.addr))
            except (ConnectionError, OSError):
                pass
            cb = self._watches.get((msg.pool_id, msg.oid))
            if cb is not None:
                try:
                    res = cb(msg.oid, msg.payload)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    import traceback

                    traceback.print_exc()  # a broken callback must be loud
            return
        if isinstance(msg, (MMapReply, MCreatePoolReply, MConfigReply,
                            MAuthTicketReply, MSnapOpReply)):
            # the mon echoes our per-RPC tid (like MOSDOp's reqid): a reply
            # landing after its RPC timed out has a stale tid and is dropped
            # instead of fulfilling the next RPC's future
            if (
                self._mon_fut
                and not self._mon_fut.done()
                and msg.tid == self._mon_tid
            ):
                self._mon_fut.set_result(msg)
        elif isinstance(msg, MOSDOpReply):
            fut = self._replies.pop(msg.reqid, None)
            if fut and not fut.done():
                fut.set_result(msg)

    @property
    def mon_addr(self) -> Tuple[str, int]:
        return self.mons.current

    async def _mon_rpc(self, msg):
        async with self._mon_lock:
            last: Exception = TimeoutError("no mon reachable")
            for _ in range(len(self.mons)):
                self._mon_tid = msg.tid = uuid.uuid4().hex
                self._mon_fut = asyncio.get_running_loop().create_future()
                try:
                    await self.messenger.send(self.mons.current, msg)
                    return await asyncio.wait_for(self._mon_fut, timeout=5)
                except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                    last = e
                    self.mons.rotate()
            raise last

    async def refresh_map(self, min_epoch: int = 0) -> OSDMap:
        """Fetch the cluster map; with ``min_epoch``, poll until we hold
        AT LEAST that epoch (the Objecter's epoch barrier — a retryable
        error reply names the OSD's epoch, and re-targeting on anything
        older would recompute the same stale primary).  The mon answers
        with an incremental chain from our epoch when it can (subscriber
        protocol); otherwise a full map."""
        import pickle as _pickle

        for _ in range(20):
            since = self.osdmap.epoch if self.osdmap is not None else 0
            reply = await self._mon_rpc(MGetMap(min_epoch=since))
            if reply.osdmap is not None:
                self.osdmap = reply.osdmap
            elif getattr(reply, "incrementals", None) and self.osdmap is not None:
                # apply the delta chain to a copy; a broken chain falls
                # back to a full fetch next iteration
                m = _pickle.loads(_pickle.dumps(self.osdmap, protocol=5))
                if all(m.apply_incremental(inc) for inc in reply.incrementals):
                    self.osdmap = m
                else:
                    self.osdmap = (await self._mon_rpc(MGetMap())).osdmap
            if min_epoch <= 0 or (self.osdmap is not None
                                  and self.osdmap.epoch >= min_epoch):
                break
            await asyncio.sleep(0.1)
        if self._watches:
            self._kick_relinger()
        return self.osdmap

    async def create_pool(
        self, name: str, pool_type: str = "ec", pg_num: int = 8,
        profile: Optional[Dict[str, str]] = None,
    ) -> int:
        reply = await self._mon_rpc(
            MCreatePool(name=name, pool_type=pool_type, pg_num=pg_num,
                        profile=profile or {})
        )
        if not reply.ok:
            raise RadosError(reply.error)
        await self.refresh_map()
        return reply.pool_id

    async def config_set(self, key: str, value: str) -> None:
        """Centralized config: `ceph config set` equivalent (replicated by
        the mon quorum, distributed to daemons at boot)."""
        reply = await self._mon_rpc(MConfigSet(key=key, value=str(value)))
        if not reply.ok:
            raise RadosError(reply.error)

    async def config_get(self, key: str = "") -> Dict[str, str]:
        reply = await self._mon_rpc(MConfigGet(key=key))
        return reply.values

    async def set_upmap(self, pool_id: int, pg: int,
                        acting: Optional[List[int]] = None) -> None:
        """Install (or clear, with acting=None) a persistent placement
        override — `ceph osd pg-upmap-items` role."""
        await self._mon_rpc(MSetUpmap(pool_id=pool_id, pg=pg,
                                      acting=list(acting or [])))
        await self.refresh_map()

    async def pool_set(self, pool_id: int, key: str, value) -> None:
        """`ceph osd pool set` role (pg_num drives PG splitting)."""
        await self._mon_rpc(MPoolSet(pool_id=pool_id, key=key,
                                     value=str(value)))
        await self.refresh_map()

    async def delete_pool(self, pool_id: int, confirm_name: str) -> None:
        """`ceph osd pool rm` role: `confirm_name` must echo the pool's
        name (the reference's --yes-i-really-really-mean-it guard).
        OSDs purge the pool's data when they see it gone from the map."""
        reply = await self._mon_rpc(MDeletePool(pool_id=pool_id,
                                                confirm_name=confirm_name))
        if not reply.ok:
            raise RadosError(reply.error)
        await self.refresh_map()

    async def mark_osd_down(self, osd_id: int) -> None:
        """Admin: immediately mark an OSD down+out (test/thrash hook)."""
        await self._mon_rpc(MMarkDown(osd_id=osd_id))
        await self.refresh_map()

    # -- data ops -------------------------------------------------------------

    def _calc_target(self, op: MOSDOp) -> Optional[int]:
        """object -> PG -> primary on the current map (reference
        Objecter::_calc_target, Objecter.cc:2764)."""
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return None
        pg = self.osdmap.object_to_pg(pool, op.oid)
        acting = self.osdmap.pg_to_acting(pool, pg)
        return self.osdmap.primary_of(acting, seed=(op.pool_id << 20) | pg)

    async def _op(self, op: MOSDOp, retries: int = 6) -> MOSDOpReply:
        """Objecter-grade submit (reference op_submit/_calc_target/_send_op,
        Objecter.cc:2257,2764,3233): ONE reqid for the op's whole lifetime
        (server dedupe = exactly-once), re-target on every map change, and
        an epoch barrier on retryable errors — the error reply names the
        OSD's epoch and we refresh to AT LEAST that before recomputing the
        target, so a stale map cannot bounce the op between two OSDs that
        each think the other is primary."""
        if self.osdmap is None:
            await self.refresh_map()
        last_error = "no attempt"
        last_code = 0
        # ONE reqid per logical op: resends carry the same id so the PG
        # log's dup detection can recognize them (reference osd_reqid_t)
        op.reqid = uuid.uuid4().hex
        fence = 0  # minimum epoch the next target may be computed on
        refresh_next = False  # one refresh owed (transport blip)
        for attempt in range(retries):
            if fence > self.osdmap.epoch or (attempt and fence == 0) \
                    or refresh_next:
                refresh_next = False
                try:
                    await self.refresh_map(min_epoch=fence)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    last_error = "map refresh failed"
                    await asyncio.sleep(0.3 * (attempt + 1))
                    continue
            pool = self.osdmap.pools.get(op.pool_id)
            if pool is None:
                # a lagging mon may have served us a pre-creation map:
                # refresh-and-retry (Objecter catches up across epochs)
                if attempt == retries - 1:
                    raise RadosError(f"pool {op.pool_id} does not exist",
                                     code=-errno.ENOENT)
                last_error = (
                    f"pool {op.pool_id} not in map epoch {self.osdmap.epoch}")
                fence = self.osdmap.epoch + 1
                await asyncio.sleep(0.2 * (attempt + 1))
                continue
            primary = self._calc_target(op)
            if primary is None:
                last_error = "no primary (all acting osds down)"
                fence = self.osdmap.epoch + 1
                await asyncio.sleep(0.3 * (attempt + 1))
                continue
            op.epoch = self.osdmap.epoch
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._replies[op.reqid] = fut
            try:
                await self.messenger.send(self.osdmap.addr_of(primary), op)
                reply = await asyncio.wait_for(fut, timeout=self.op_timeout)
                if reply.ok:
                    return reply
                last_error = reply.error
                # classification is by TYPED code (reference 0/-errno):
                # a reworded error string can never silently change an
                # op's retry behavior
                code = last_code = getattr(reply, "code", 0)
                if code in _DEFINITIVE_CODES:
                    raise RadosError(
                        f"op {op.op} {op.oid} failed: {reply.error}",
                        code=code)
                # epoch barrier: never re-target on a map older than the
                # replying OSD's (it refused exactly because placement
                # moved — recomputing on our stale map re-picks it)
                fence = max(fence, getattr(reply, "map_epoch", 0))
                if code in (-errno.ESTALE, -errno.EAGAIN):
                    # placement moved / PG degraded: both are cured by a
                    # newer map — fence PAST our own epoch, growing window
                    # while detection + recovery move seats.  A server-
                    # provided backoff (MOSDBackoff role) extends the
                    # pause: the PG told us how long it wants.
                    fence = max(fence, self.osdmap.epoch + 1)
                    pause = max(getattr(reply, "backoff", 0.0),
                                min(0.25 * attempt, 1.0) if attempt else 0.0)
                    if pause:
                        await asyncio.sleep(pause)
                    continue
                # -EBUSY and anything unclassified: prompt plain retry
                await asyncio.sleep(0.2 * (attempt + 1))
            except PermissionError:
                # expired/rotated-away ticket: fetch a fresh one and retry
                last_error = "ticket rejected"
                try:
                    await self._fetch_ticket()
                except Exception:
                    await asyncio.sleep(0.2 * (attempt + 1))
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                last_error = f"{type(e).__name__}: {e}"
                last_code = 0  # transport failure: no typed OSD answer
                # the target may have died — but a transport blip has NO
                # map change coming, so the next attempt refreshes to the
                # CURRENT map (one RPC at loop top), not a future epoch
                # (a 2s poll per blip).  If the target is unchanged the
                # resend is dedupe-safe; if the OSD really died, failure
                # detection bumps the epoch and re-targets us.
                refresh_next = True
                await asyncio.sleep(0.2 * (attempt + 1))
            finally:
                self._replies.pop(op.reqid, None)
        raise RadosError(f"op {op.op} {op.oid} failed: {last_error}",
                         code=last_code)

    @staticmethod
    def _check_oid(oid: str) -> None:
        if SNAP_SEP in oid:
            raise RadosError("oid contains the reserved snap separator",
                             code=-errno.EINVAL)

    def _write_snapc(self, pool_id: int, snapc):
        """The SnapContext a write carries: the caller's, or — for a
        pool in pool-snaps mode — the POOL's own context from the
        osdmap (reference IoCtxImpl: the ioctx snapc defaults to the
        pool snapc), so every writer path clones pre-snap heads without
        knowing pool snapshots exist."""
        if snapc:
            return snapc
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is not None and getattr(pool, "snap_mode", "") == "pool":
            return pool.pool_snapc()
        return (0, [])

    async def put(self, pool_id: int, oid: str, data: bytes,
                  offset: Optional[int] = None,
                  snapc: Optional[Tuple[int, List[int]]] = None) -> None:
        """Full-object write, or a partial overwrite at `offset` (the
        primary takes the read-modify-write path).  ``snapc`` is a
        self-managed snap context (seq, snaps-descending): the primary
        clones the head before the first write past a new snap
        (reference SnapContext on every write)."""
        self._check_oid(oid)
        seq, snaps = self._write_snapc(pool_id, snapc)
        await self._op(MOSDOp(op="write", pool_id=pool_id, oid=oid, data=data,
                              offset=-1 if offset is None else int(offset),
                              snapc_seq=seq, snapc_snaps=list(snaps)))

    async def multi(self, pool_id: int, oid: str, ops,
                    snapc: Optional[Tuple[int, List[int]]] = None):
        """Compound atomic op (reference MOSDOp vector<OSDOp> /
        ObjectWriteOperation): `ops` is an ordered list of (name, kwargs)
        sub-ops executed all-or-nothing on one object.  Returns
        (per-sub-op results, object version the op observed); a failing
        sub-op raises RadosError with its typed code and nothing
        applied."""
        import pickle as _pickle

        self._check_oid(oid)
        seq, snaps = self._write_snapc(pool_id, snapc)
        reply = await self._op(MOSDOp(op="multi", pool_id=pool_id, oid=oid,
                                      ops=list(ops), snapc_seq=seq,
                                      snapc_snaps=list(snaps)))
        return _pickle.loads(reply.data), reply.version

    # -- self-managed snapshots (reference IoCtxImpl selfmanaged_snap_*) ----

    async def selfmanaged_snap_create(self, pool_id: int) -> int:
        """Allocate a new cluster-unique snap id (the mon is the
        allocator)."""
        reply = await self._mon_rpc(MSnapOp(pool_id=pool_id, op="create"))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        return reply.snap_id

    async def selfmanaged_snap_remove(self, pool_id: int,
                                      snap_id: int) -> None:
        """Mark the snap removed in the pool and trim its clones
        (reference snap trimmer).  Trim is best-effort immediate and
        idempotent: an OSD that was down during the fan-out keeps its
        clones until this call is re-run (the mon records the removal
        first, so re-running re-trims everywhere)."""
        reply = await self._mon_rpc(
            MSnapOp(pool_id=pool_id, op="remove", snap_id=snap_id))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        for osd_id in self._pg_primaries(pool_id):
            try:
                await self._op_direct(osd_id, MOSDOp(
                    op="snap-trim", pool_id=pool_id, snap_id=snap_id))
            except RadosError:
                continue

    # -- pool-managed snapshots (reference `ceph osd pool mksnap`,
    # OSDMonitor pool-op SNAP_CREATE/SNAP_RM; mixing with self-managed
    # snaps is a typed -EINVAL at the mon) ----------------------------------

    async def pool_snap_create(self, pool_id: int, name: str) -> int:
        """Create a mon-managed pool snapshot; every subsequent write
        carries the pool's SnapContext, so heads clone lazily on first
        overwrite (the same make_writeable machinery as self-managed
        snaps)."""
        reply = await self._mon_rpc(
            MSnapOp(pool_id=pool_id, op="mksnap", name=name))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        return reply.snap_id

    async def pool_snap_remove(self, pool_id: int, name: str) -> None:
        """Remove a pool snapshot and trim its clones (same fan-out
        discipline as selfmanaged_snap_remove: mon records first, trim
        is idempotent best-effort)."""
        reply = await self._mon_rpc(
            MSnapOp(pool_id=pool_id, op="rmsnap", name=name))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        for osd_id in self._pg_primaries(pool_id):
            try:
                await self._op_direct(osd_id, MOSDOp(
                    op="snap-trim", pool_id=pool_id,
                    snap_id=reply.snap_id))
            except RadosError:
                continue

    async def rollback_object(self, pool_id: int, oid: str, snap_id: int,
                              snapc=None) -> None:
        """Restore one object's head to its state at `snap_id`
        (reference rollback: read-at-snap -> write head; an object
        absent at the snap is removed).  The ONE implementation behind
        ioctx self-managed rollback, pool-snap rollback, and the rados
        CLI."""
        try:
            old = await self.get(pool_id, oid, snap=snap_id)
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            await self.delete(pool_id, oid, snapc=snapc)
            return
        await self.put(pool_id, oid, old, snapc=snapc)

    async def pool_snap_list(self, pool_id: int) -> Dict[str, int]:
        await self.refresh_map()
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            raise RadosError(f"pool {pool_id} does not exist",
                             code=-errno.ENOENT)
        return dict(getattr(pool, "pool_snaps", {}) or {})

    async def osd_statfs(self, osd_id: int) -> Dict:
        """One OSD's store utilization (reference ObjectStore::statfs
        feeding `ceph osd df`)."""
        import json as _json

        reply = await self._op_direct(osd_id, MOSDOp(op="statfs"))
        return _json.loads(reply.data)

    async def deep_scrub(self, pool_id: int) -> Dict[str, int]:
        """Ask every up OSD to deep-scrub the PGs it leads; sums the
        per-primary summaries."""
        import pickle as _pickle

        total = {"scrubbed": 0, "errors": 0, "repaired": 0}
        for osd_id in self._pg_primaries(pool_id):
            try:
                reply = await self._op_direct(
                    osd_id, MOSDOp(op="deep-scrub", pool_id=pool_id))
                for k, v in _pickle.loads(reply.data).items():
                    total[k] = total.get(k, 0) + v
            except RadosError:
                continue
        return total

    async def get(self, pool_id: int, oid: str, snap: int = 0) -> bytes:
        """Read the head, or the object's state AT a snap id (resolved
        through the primary's SnapSet clone list)."""
        self._check_oid(oid)
        reply = await self._op(MOSDOp(op="read", pool_id=pool_id, oid=oid,
                                      snap_read=int(snap)))
        data = reply.data
        if isinstance(data, BufferList):
            # colocated fastpath hands the primary's scatter-gather read
            # reply over by reference; materialize at the API boundary
            # (the wire path already delivered one contiguous buffer)
            data = data.tobytes()
        return data

    async def delete(self, pool_id: int, oid: str,
                     snapc: Optional[Tuple[int, List[int]]] = None) -> None:
        """Delete the head; under a snap context the primary clones
        first and leaves a whiteout so snapshots keep resolving."""
        self._check_oid(oid)
        seq, snaps = self._write_snapc(pool_id, snapc)
        await self._op(MOSDOp(op="delete", pool_id=pool_id, oid=oid,
                              snapc_seq=seq, snapc_snaps=list(snaps)))

    async def watch(self, pool_id: int, oid: str, callback) -> None:
        """Register a notify callback on oid (librados watch2 role).
        Watches are LINGER ops (reference Objecter::linger_watch): the
        client tracks the registered primary and automatically
        re-registers when a map refresh shows the primary moved — the
        new primary has no watcher state for us until then."""
        import pickle as _pickle

        self._watches[(pool_id, oid)] = callback
        try:
            await self._op(MOSDOp(op="watch", pool_id=pool_id, oid=oid,
                                  data=_pickle.dumps(self.messenger.addr)))
        except BaseException:
            self._watches.pop((pool_id, oid), None)  # registration failed
            raise
        self._watch_primaries[(pool_id, oid)] = self._primary_for(pool_id, oid)
        if self._linger_poll_task is None or self._linger_poll_task.done():
            # an IDLE watcher issues no ops, so nothing would ever pull a
            # new map: poll while watches exist (reference: the Objecter
            # subscribes to maps; this is the polling analog)
            self._linger_poll_task = asyncio.get_running_loop().create_task(
                self._linger_poll())

    async def _linger_poll(self) -> None:
        interval = float(self.conf.get("client_linger_poll", 1.0) or 1.0)
        while self._watches:
            await asyncio.sleep(interval)
            if not self._watches:
                break
            try:
                await self.refresh_map()  # _kick_relinger rides this
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass

    def _primary_for(self, pool_id: int, oid: str) -> Optional[int]:
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is None:
            return None
        pg = self.osdmap.object_to_pg(pool, oid)
        acting = self.osdmap.pg_to_acting(pool, pg)
        return self.osdmap.primary_of(acting, seed=(pool_id << 20) | pg)

    def _kick_relinger(self) -> None:
        """After a map change: re-register watches whose primary moved
        (on a task of its own — refresh_map runs inside op retries and
        must not recurse into more ops)."""
        stale = [key for key, registered in self._watch_primaries.items()
                 if key in self._watches
                 and self._primary_for(*key) not in (None, registered)]
        if not stale or (self._relinger_task
                         and not self._relinger_task.done()):
            return

        async def _relinger() -> None:
            import pickle as _pickle

            for pool_id, oid in stale:
                if (pool_id, oid) not in self._watches:
                    continue  # unwatched meanwhile
                try:
                    await self._op(MOSDOp(
                        op="watch", pool_id=pool_id, oid=oid,
                        data=_pickle.dumps(self.messenger.addr)))
                    self._watch_primaries[(pool_id, oid)] = \
                        self._primary_for(pool_id, oid)
                except RadosError:
                    pass  # next map change retries

        self._relinger_task = asyncio.get_running_loop().create_task(
            _relinger())

    async def unwatch(self, pool_id: int, oid: str) -> None:
        import pickle as _pickle

        await self._op(MOSDOp(op="unwatch", pool_id=pool_id, oid=oid,
                              data=_pickle.dumps(self.messenger.addr)))
        self._watches.pop((pool_id, oid), None)  # only after the OSD agreed
        self._watch_primaries.pop((pool_id, oid), None)

    async def notify(self, pool_id: int, oid: str,
                     payload: bytes = b"") -> List:
        """Notify watchers; returns the list of watcher addrs that acked
        (librados notify2 reply role)."""
        import pickle as _pickle

        reply = await self._op(MOSDOp(op="notify", pool_id=pool_id, oid=oid,
                                      data=payload))
        return _pickle.loads(reply.data)

    async def list_objects(self, pool_id: int,
                           nspace: str = "") -> List[str]:
        """Paginated per-PG-primary listing (reference pgls/do_pgnls):
        admin listings scale with PG count, never cluster size.  Falls
        back to the all-OSD union for a PG whose primary cannot answer
        (mid-peering) — correctness over elegance for admin tooling.
        `nspace` filters server-side ("" = default namespace,
        ALL_NSPACES = everything); returned names are WIRE names — the
        IoCtx strips its namespace prefix for its callers."""
        if self.osdmap is None:
            await self.refresh_map()
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            # our map may predate the pool: one refresh before concluding
            await self.refresh_map()
            pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            raise RadosError(f"pool {pool_id} does not exist",
                             code=-errno.ENOENT)
        oids: set = set()
        fallback = False
        for pg in range(pool.pg_num):
            acting = self.osdmap.pg_to_acting(pool, pg)
            primary = self.osdmap.primary_of(acting,
                                             seed=(pool_id << 20) | pg)
            if primary is None:
                fallback = True
                continue
            cursor = ""
            while True:
                try:
                    reply = await self._op_direct(primary, MOSDOp(
                        op="pgls", pool_id=pool_id, pg=pg, cursor=cursor,
                        nspace=nspace))
                except RadosError:
                    fallback = True
                    break
                oids.update(reply.oids)
                cursor = getattr(reply, "cursor", "")
                if not cursor:
                    break
        if fallback:
            # degraded path: union of per-OSD listings covers the holes
            for osd in self.osdmap.osds.values():
                if not osd.up:
                    continue
                try:
                    reply = await self._op_direct(
                        osd.osd_id, MOSDOp(op="list", pool_id=pool_id,
                                           nspace=nspace))
                    oids.update(reply.oids)
                except RadosError:
                    continue
        return sorted(oids)

    def _pg_primaries(self, pool_id: int) -> List[int]:
        """The distinct primaries of a pool's PGs — the scrub/repair
        fan-out set (per-PG primaries, not every OSD in the cluster)."""
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return []
        primaries = set()
        for pg in range(pool.pg_num):
            acting = self.osdmap.pg_to_acting(pool, pg)
            p = self.osdmap.primary_of(acting, seed=(pool_id << 20) | pg)
            if p is not None:
                primaries.add(p)
        return sorted(primaries)

    async def repair_pool(self, pool_id: int) -> None:
        """Primary-led repair, fanned out to the pool's PG primaries."""
        for osd_id in self._pg_primaries(pool_id):
            try:
                await self._op_direct(osd_id,
                                      MOSDOp(op="repair", pool_id=pool_id))
            except RadosError:
                continue

    async def _op_direct(self, osd_id: int, op: MOSDOp) -> MOSDOpReply:
        op.reqid = uuid.uuid4().hex
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._replies[op.reqid] = fut
        try:
            await self.messenger.send(self.osdmap.addr_of(osd_id), op)
            reply = await asyncio.wait_for(fut, timeout=self.op_timeout)
        except (ConnectionError, OSError, asyncio.TimeoutError) as e:
            raise RadosError(str(e))
        finally:
            self._replies.pop(op.reqid, None)
        if not reply.ok:
            raise RadosError(reply.error, code=getattr(reply, "code", 0))
        return reply
