"""Lock-order cycle detection (reference src/common/lockdep.cc).

The reference's debug mutexes register every (held -> acquiring) lock
pair in a global order graph and assert when an acquisition would close
a cycle — catching ABBA deadlocks the first time the ORDER is violated,
not the (possibly never-reproduced) time the threads actually interleave
into the deadlock.  This is that machinery for a codebase that mixes
real threads (BatchingQueue worker, native calls) with asyncio tasks
(daemons): both lock flavors funnel into one order graph, keyed by the
execution context (thread id for threads, task id for tasks).

Engagement mirrors the reference's debug-build gating: OFF unless
``CEPH_TPU_LOCKDEP=1`` (or ``enable()`` is called), because the graph
bookkeeping costs a dict walk per acquisition.  ``make_mutex(name)`` /
``make_async_mutex(name)`` return plain primitives when disabled, so
production hot paths pay nothing.

A violation raises ``LockOrderError`` naming the cycle — tests assert on
it; daemons run with it disabled unless debugging.
"""

from __future__ import annotations

import asyncio
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

_ENABLED = os.environ.get("CEPH_TPU_LOCKDEP") == "1"

# order graph: edge (a, b) means "a was held while acquiring b"; a cycle
# through the graph is a potential deadlock.  Guarded by _GRAPH_LOCK (a
# plain lock — it is never held while taking a tracked lock).
_EDGES: Dict[str, Set[str]] = {}
_GRAPH_LOCK = threading.Lock()

# held stack per execution context
_HELD: Dict[Tuple[str, int], List[str]] = {}
_HELD_LOCK = threading.Lock()


class LockOrderError(RuntimeError):
    """An acquisition would close a cycle in the global lock order."""


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    """Clear the order graph (tests)."""
    with _GRAPH_LOCK:
        _EDGES.clear()
    with _HELD_LOCK:
        _HELD.clear()


def _ctx_key() -> Tuple[str, int]:
    try:
        task = asyncio.current_task()
    except RuntimeError:
        task = None
    if task is not None:
        return ("task", id(task))
    return ("thread", threading.get_ident())


def _find_path(frm: str, to: str) -> Optional[List[str]]:
    """DFS: an existing path frm -> to means adding edge to -> frm would
    close a cycle."""
    stack, seen = [(frm, [frm])], {frm}
    while stack:
        node, path = stack.pop()
        if node == to:
            return path
        for nxt in _EDGES.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def will_lock(name: str) -> None:
    """Record intent to acquire `name`; raises LockOrderError when the
    acquisition inverts an established order (the lockdep check)."""
    key = _ctx_key()
    with _HELD_LOCK:
        held = list(_HELD.get(key, ()))
    if not held:
        return
    with _GRAPH_LOCK:
        for h in held:
            if h == name:
                continue  # recursive acquisition: not an order edge
            # adding h -> name: would name -> ... -> h already exist?
            path = _find_path(name, h)
            if path is not None:
                raise LockOrderError(
                    f"lock order violation: acquiring {name!r} while "
                    f"holding {h!r}, but the established order is "
                    f"{' -> '.join(path)} -> {name!r} (cycle)")
            _EDGES.setdefault(h, set()).add(name)


def locked(name: str) -> None:
    key = _ctx_key()
    with _HELD_LOCK:
        _HELD.setdefault(key, []).append(name)


def unlocked(name: str) -> None:
    key = _ctx_key()
    with _HELD_LOCK:
        held = _HELD.get(key)
        if held and name in held:
            held.reverse()
            held.remove(name)  # innermost matching acquisition
            held.reverse()
            if not held:
                _HELD.pop(key, None)


class DebugLock:
    """threading.Lock with lockdep tracking (ceph::mutex_debug role)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        will_lock(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            locked(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        unlocked(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


class DebugAsyncLock:
    """asyncio.Lock with lockdep tracking: the same order graph catches
    an asyncio task locking A-then-B against a worker thread locking
    B-then-A — the cross-runtime inversions a thread-only lockdep never
    sees."""

    def __init__(self, name: str):
        self.name = name
        self._lock = asyncio.Lock()

    async def acquire(self) -> bool:
        will_lock(self.name)
        await self._lock.acquire()
        locked(self.name)
        return True

    def release(self) -> None:
        self._lock.release()
        unlocked(self.name)

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


def make_mutex(name: str):
    """A threading lock: debug-tracked when lockdep is enabled, plain
    otherwise (zero hot-path cost in production)."""
    return DebugLock(name) if _ENABLED else threading.Lock()


def make_async_mutex(name: str):
    return DebugAsyncLock(name) if _ENABLED else asyncio.Lock()
