"""Typed option tables + runtime config proxy.

Role-equivalent of the reference's md_config_t/ConfigProxy
(reference src/common/config.cc) and the YAML option schemas
(src/common/options/{global,mon,osd}.yaml.in): every option is declared once
with a type, default, level (basic/advanced/dev) and flags (startup options
cannot change at runtime; runtime options notify registered observers on
change).  Sources are layered the way the reference layers ceph.conf < env <
CLI < mon-centralized config: ``set_source(name, values)`` installs a source
at a priority, and effective values are resolved highest-priority-first.

Observers mirror md_config_obs_t (src/common/config_obs.h): a subscriber
names the keys it tracks and gets ``handle_conf_change(config, changed)``
callbacks, the mechanism ThreadPool uses to resize itself at runtime
(src/common/WorkQueue.h:44).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

OPT_STR = "str"
OPT_INT = "int"
OPT_FLOAT = "float"
OPT_BOOL = "bool"
OPT_SIZE = "size"  # accepts 4K/1M/2G suffixes
OPT_SECS = "secs"  # accepts 500ms/2s/1m suffixes

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"

FLAG_STARTUP = "startup"  # read once at daemon start; runtime set -> error
FLAG_RUNTIME = "runtime"  # observers notified on change
FLAG_CLUSTER = "cluster"  # distributed via the ConfigMonitor

_SIZE_SUFFIX = {"": 1, "b": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30,
                "t": 1 << 40}
_SECS_SUFFIX = {"": 1.0, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


@dataclass
class Option:
    name: str
    type: str = OPT_STR
    default: Any = None
    level: str = LEVEL_ADVANCED
    flags: Tuple[str, ...] = (FLAG_RUNTIME,)
    desc: str = ""
    min: Optional[float] = None
    max: Optional[float] = None
    enum_values: Tuple[str, ...] = ()

    def parse(self, value: Any) -> Any:
        if value is None:
            return None
        if self.type == OPT_STR:
            out: Any = str(value)
            if self.enum_values and out not in self.enum_values:
                raise ValueError(
                    f"{self.name}: {out!r} not in {sorted(self.enum_values)}"
                )
            return out
        if self.type == OPT_BOOL:
            if isinstance(value, bool):
                return value
            s = str(value).strip().lower()
            if s in ("1", "true", "yes", "on"):
                return True
            if s in ("0", "false", "no", "off"):
                return False
            raise ValueError(f"{self.name}: bad bool {value!r}")
        if self.type == OPT_INT:
            out = int(value)
        elif self.type == OPT_FLOAT:
            out = float(value)
        elif self.type == OPT_SIZE:
            out = self._parse_suffixed(value, _SIZE_SUFFIX, int)
        elif self.type == OPT_SECS:
            out = self._parse_suffixed(value, _SECS_SUFFIX, float)
        else:
            raise ValueError(f"{self.name}: unknown option type {self.type}")
        if self.min is not None and out < self.min:
            raise ValueError(f"{self.name}: {out} < min {self.min}")
        if self.max is not None and out > self.max:
            raise ValueError(f"{self.name}: {out} > max {self.max}")
        return out

    def _parse_suffixed(self, value: Any, table: Dict[str, float], cast) -> Any:
        if isinstance(value, (int, float)):
            return cast(value)
        m = re.fullmatch(r"\s*([0-9.]+)\s*([a-zA-Z]*)\s*", str(value))
        if not m:
            raise ValueError(f"{self.name}: bad value {value!r}")
        suffix = m.group(2).lower().rstrip("ib") or m.group(2).lower()
        # allow 4K / 4KB / 4KiB; 500ms stays "ms"
        if suffix not in table:
            suffix = m.group(2).lower()
        if suffix not in table:
            raise ValueError(f"{self.name}: bad suffix in {value!r}")
        return cast(float(m.group(1)) * table[suffix])


def _opts(*options: Option) -> Dict[str, Option]:
    return {o.name: o for o in options}


# Default schema: the subset of the reference's option tables this framework
# consumes, same names where the semantic carries over
# (src/common/options/global.yaml.in, mon.yaml.in, osd.yaml.in).
DEFAULT_SCHEMA: Dict[str, Option] = _opts(
    # EC plugin machinery (global.yaml.in:437,2507,2516; mon.yaml.in:16)
    Option("erasure_code_dir", OPT_STR, "", flags=(FLAG_STARTUP,),
           desc="directory to dlopen native EC plugins from"),
    Option("osd_erasure_code_plugins", OPT_STR,
           "jerasure isa shec lrc clay tpu", flags=(FLAG_STARTUP,),
           desc="plugins preloaded at daemon start"),
    Option("osd_pool_default_erasure_code_profile", OPT_STR,
           "plugin=jerasure technique=reed_sol_van k=2 m=2"),
    Option("osd_pool_erasure_code_stripe_unit", OPT_SIZE, 4096),
    # messenger (global.yaml.in:1240-1265)
    Option("ms_inject_socket_failures", OPT_INT, 0, level=LEVEL_DEV),
    Option("ms_inject_delay_max", OPT_SECS, 0.0, level=LEVEL_DEV),
    Option("ms_crc_data", OPT_BOOL, True),
    Option("ms_local_fastpath", OPT_BOOL, False,
           desc="colocated vstart daemons skip the wire for same-process "
                "peers (implies ms_colocated_ring unless set explicitly)"),
    Option("ms_compress_min_size", OPT_SIZE, 0,
           desc="compress frames >= this size; 0 disables on-wire compression"),
    Option("ms_dispatch_throttle_bytes", OPT_SIZE, 100 << 20),
    Option("ms_trace_propagation", OPT_BOOL, True,
           desc="stamp trace-id/parent-span fields onto data-plane "
                "messages so cross-daemon spans stitch into one tree"),
    Option("ms_auth_secret", OPT_STR, "",
           desc="shared cluster secret; non-empty enables cephx-style frames"),
    # sharded multi-reactor wire plane (reference AsyncMessenger worker
    # pool, src/msg/async/AsyncMessenger.h ms_async_op_threads)
    Option("ms_async_op_threads", OPT_INT, 0, flags=(FLAG_STARTUP,),
           desc="reactor workers per messenger, each its own event loop "
                "owning a socket shard (0 = single-loop legacy path; "
                "in ms_reactor_mode=process, 0 defaults to 2 workers)"),
    Option("ms_reactor_mode", OPT_STR, "thread", flags=(FLAG_STARTUP,),
           desc="reactor worker substrate: 'thread' (N event-loop "
                "threads sharing the interpreter, the r13 plane) or "
                "'process' (forked wire workers, each owning its socket "
                "shard + its own wirepath arm; frames cross via "
                "shared-memory rings into the home-loop dispatch pump). "
                "The CEPH_TPU_REACTOR env overrides process-wide."),
    Option("ms_shm_ring_bytes", OPT_SIZE, 4 << 20, flags=(FLAG_STARTUP,),
           desc="per-direction shared-memory ring capacity of one "
                "process-delegated connection; oversized frames stream "
                "through in bounded pieces instead of deadlocking"),
    Option("ms_lanes_per_peer", OPT_INT, 1, flags=(FLAG_STARTUP,), min=1,
           desc="parallel lanes per peer session (negotiated; lane 0 is "
                "control-only, data stripes across the rest; 1 = single "
                "connection)"),
    Option("ms_lane_stripe_min", OPT_SIZE, 1 << 20,
           desc="blobs at least this large fragment across ALL data "
                "lanes concurrently (0 disables fragmentation)"),
    Option("ms_colocated_ring", OPT_BOOL, False,
           desc="negotiate a zero-serialization in-process ring with "
                "colocated peers at connect time (falls back to TCP)"),
    Option("ms_wirepath_native", OPT_BOOL, True, flags=(FLAG_STARTUP,),
           desc="run the messenger's per-byte hot loop (frame crc, "
                "scatter/gather, writev) through the released-GIL native "
                "wirepath when it builds; False forces the python arm "
                "(the CEPH_TPU_WIREPATH=0 env forces it process-wide)"),
    # auth (reference auth_supported / cephx ticket lifetime)
    Option("auth_cephx", OPT_BOOL, False,
           desc="require cephx-style ticket auth on daemon connections"),
    Option("auth_ticket_ttl", OPT_SECS, 3600.0,
           desc="service-ticket lifetime the mon seals into tickets"),
    # client / objecter (reference objecter_timeout, rados_osd_op_timeout)
    Option("client_name", OPT_STR, "",
           desc="entity name stamped on MOSDOp ops (QoS tenant identity; "
                "empty = anonymous, riding the pool default profile)"),
    Option("client_op_timeout", OPT_SECS, 10.0,
           desc="per-attempt op timeout before the client retargets"),
    Option("client_op_deadline", OPT_SECS, 0.0,
           desc="overall op deadline across retries (0 = retry forever)"),
    Option("client_backoff_base", OPT_SECS, 0.1,
           desc="first retry delay for retryable op errors"),
    Option("client_backoff_cap", OPT_SECS, 2.0,
           desc="retry delay ceiling (exponential backoff cap)"),
    Option("client_backoff_park_max", OPT_SECS, 3.0,
           desc="default park ceiling for an MOSDBackoff block whose "
                "unblock is lost (the server's duration wins when set)"),
    Option("client_linger_poll", OPT_SECS, 1.0,
           desc="watch re-register / linger ping cadence"),
    # mgr (reference mgr module tick / target per-PG object count)
    Option("mgr_addr", OPT_STR, "",
           desc="host:port the mgr's metrics endpoint binds (daemons "
                "learn it via the centralized config)"),
    Option("mgr_balancer", OPT_BOOL, False,
           desc="enable the upmap balancer module"),
    Option("mgr_pg_autoscaler", OPT_BOOL, False,
           desc="enable the pg_num autoscaler module"),
    Option("mgr_module_interval", OPT_SECS, 5.0,
           desc="mgr module tick cadence (balancer/autoscaler)"),
    Option("mgr_health_interval", OPT_SECS, 1.0,
           desc="mgr health-poll cadence against the mon"),
    Option("mgr_target_objects_per_pg", OPT_INT, 32,
           desc="autoscaler split threshold, objects per PG"),
    # mon (reference mon_osd_min_down_reporters / reporter grace)
    Option("mon_osd_report_grace", OPT_SECS, 1.5,
           desc="seconds without a ping before the mon marks an OSD down"),
    Option("mon_osd_min_down_reporters", OPT_INT, 1,
           desc="distinct OSD failure reports required before the mon "
                "marks the target down ahead of its own grace"),
    Option("mon_osd_down_out_interval", OPT_SECS, 0.6,
           desc="seconds an OSD stays down before the mon auto-marks it "
                "out (0 disables auto-out; the `noout` osdmap flag and "
                "mon_osd_min_in_ratio both gate the transition)"),
    Option("mon_osd_min_in_ratio", OPT_FLOAT, 0.0, min=0.0,
           desc="auto-out floor: the mon refuses to auto-out an OSD when "
                "the in-fraction of the cluster would drop below this "
                "(a partition must not auto-out half the map; 0 disables "
                "— test-scaled default, the reference ships 0.75)"),
    Option("osd_crush_chooseleaf_type", OPT_STR, "osd",
           desc="default crush failure domain for new pool rules when "
                "the profile names none (chooseleaf bucket type; 'osd' "
                "keeps device-level placement)"),
    Option("crush_num_hosts", OPT_INT, 0,
           desc="vstart: spread OSDs over this many synthetic hosts in "
                "the crush map (0 = flat osd-level map)"),
    Option("admin_socket_dir", OPT_STR, "", flags=(FLAG_STARTUP,),
           desc="directory for per-daemon asok sockets; empty disables "
                "the admin socket"),
    # osd
    Option("osd_heartbeat_interval", OPT_SECS, 0.3),
    Option("osd_heartbeat_grace", OPT_SECS, 2.0),
    Option("osd_auto_repair", OPT_BOOL, True),
    Option("osd_repair_delay", OPT_SECS, 0.5),
    Option("osd_repair_full_sweep", OPT_BOOL, True,
           desc="repair re-peers with a forced backfill sweep (full "
                "listing) instead of log-only recovery"),
    Option("osd_op_num_shards", OPT_INT, 4),
    Option("osd_op_queue", OPT_STR, "wpq", enum_values=("wpq", "mclock")),
    Option("osd_pg_op_concurrency", OPT_INT, 4,
           desc="per-PG chain width: ops on one PG beyond this queue"),
    Option("osd_min_pg_log_entries", OPT_INT, 500,
           desc="PG log tail retained past the last-complete horizon"),
    Option("osd_max_backfills", OPT_INT, 4,
           desc="concurrent backfill reservations an OSD grants (the "
                "AsyncReserver slot count)"),
    Option("osd_backfill_reserve_lease", OPT_SECS, 300.0,
           desc="remote backfill reservation auto-expiry (a primary that "
                "died holding a slot cannot wedge the target forever)"),
    Option("osd_recovery_retry", OPT_SECS, 1.0,
           desc="retry cadence for recovery steps parked on missing "
                "peers or reservations"),
    Option("osd_backoff_secs", OPT_SECS, 0.5,
           desc="base MOSDBackoff block duration for a busy PG"),
    Option("osd_backoff_max", OPT_SECS, 3.0,
           desc="MOSDBackoff block duration ceiling under escalation"),
    Option("osd_deep_scrub_interval", OPT_SECS, 3600.0,
           desc="auto deep-scrub cadence per PG (osd_scrub_auto)"),
    Option("osd_auto_revert_unfound", OPT_BOOL, True,
           desc="auto-revert objects confirmed unfound to their rollback "
                "version (mark_unfound_lost revert role)"),
    Option("osd_unfound_revert_grace", OPT_SECS, 30.0,
           desc="how long an object must stay unfound (over complete "
                "listings) before auto-revert"),
    # EC device service (ceph_tpu/parallel seams)
    Option("osd_ec_stripe_unit", OPT_SIZE, 4096,
           desc="per-chunk stripe unit EC pools default to"),
    Option("osd_ec_batching", OPT_BOOL, True,
           desc="route codec work through the process-shared "
                "BatchingQueue (device dispatch coalescing)"),
    Option("osd_ec_dispatch_timeout", OPT_SECS, 0.0,
           desc="BatchingQueue device-dispatch watchdog (0 disables); "
                "trips the circuit breaker on a wedged device"),
    Option("osd_ec_planar_residency", OPT_BOOL, True,
           desc="keep encoded shard rows planar-resident on the device "
                "(PlanarShardStore cache tier)"),
    Option("osd_ec_planar_bytes", OPT_SIZE, 0,
           desc="planar residency byte budget (0 = store default)"),
    # multi-tenant QoS (reference mClockScheduler client profiles; pool
    # opts qos_reservation/qos_weight/qos_limit + qos_class:<name>
    # override these cluster defaults per pool)
    Option("osd_backoff_queue_depth", OPT_INT, 0,
           desc="sharded-queue depth past which arriving client ops are "
                "shed via MOSDBackoff (0 disables); with client "
                "identities the shed targets the most over-limit client"),
    Option("osd_qos_default_reservation", OPT_FLOAT, 100.0,
           desc="per-client guaranteed ops/sec when the pool declares "
                "no qos_reservation"),
    Option("osd_qos_default_weight", OPT_FLOAT, 10.0,
           desc="per-client share of surplus when the pool declares no "
                "qos_weight"),
    Option("osd_qos_default_limit", OPT_FLOAT, 0.0,
           desc="per-client ops/sec cap when the pool declares no "
                "qos_limit (0 = unlimited)"),
    Option("osd_qos_cost_per_io", OPT_SIZE, 65536,
           desc="bytes of op payload that cost one extra IOPS unit in "
                "the dmClock tags (byte-COST: a B-byte op tags as "
                "1 + B/this; 0 = pure per-op tagging)"),
    Option("osd_qos_arrears_cap", OPT_FLOAT, 2.0,
           desc="ceiling (seconds) on a client's accumulated over-limit "
                "arrears — bounds how long a quieted flooder stays "
                "shed-eligible"),
    Option("osd_qos_shed_grace", OPT_FLOAT, 0.25,
           desc="seconds of over-limit arrears a client may accumulate "
                "before the saturation shed targets it"),
    Option("osd_mclock_max_clients", OPT_INT, 1024,
           desc="per-shard bound on per-client dmClock states (idle "
                "states pruned oldest-first)"),
    Option("osd_mclock_profile", OPT_STR, "balanced",
           enum_values=("balanced", "high_client_ops",
                        "high_recovery_ops"),
           desc="background dmClock profile set: how the mClock "
                "scheduler splits IOPS between client, recovery, "
                "rebalance, scrub and best-effort classes "
                "(mclock_<class>_res/wgt/lim/burst override "
                "individual values)"),
    Option("osd_qos_burst_allowance", OPT_FLOAT, 0.0,
           desc="default rho/delta burst credit (seconds) a client "
                "profile banks while idle when the pool declares no "
                "qos_burst — burst*rate immediately-eligible ops"),
    Option("osd_qos_normalize_spread", OPT_BOOL, True,
           desc="divide per-client reservation/limit by the pool's "
                "primary spread so a tenant served by N OSDs gets its "
                "nominal profile cluster-wide instead of N x it"),
    Option("osd_background_qos", OPT_BOOL, True,
           desc="route backfill/recovery/scrub per-object work through "
                "the sharded op queue under background dmClock classes "
                "(off: background sweeps run unthrottled)"),
    Option("osd_qos_max_clients", OPT_INT, 4096,
           desc="bound on the admission tracker's per-client states"),
    # op tracking + slow-op health (reference osd_op_complaint_time /
    # osd_op_history_size, TrackedOp.h)
    Option("osd_op_complaint_time", OPT_SECS, 2.0,
           desc="ops older than this raise SLOW_OPS and join the "
                "historic slow ring"),
    Option("osd_op_history_size", OPT_INT, 64,
           desc="completed ops retained by dump_historic_ops"),
    Option("osd_op_history_slow_size", OPT_INT, 64,
           desc="slow completions retained by dump_historic_slow_ops"),
    Option("osd_op_tracker_max_events", OPT_INT, 128,
           desc="timeline events retained per tracked op (bound against "
                "stuck-op timeline growth)"),
    Option("osd_scrub_auto", OPT_BOOL, False),
    # cache tier (osd.yaml.in osd_tier_promote_max_*; pg_pool_t
    # hit_set_*/target_max_bytes/cache_target_full_ratio defaults —
    # pool opts set via `pool set` override these per pool)
    Option("osd_tier_enabled", OPT_BOOL, True,
           desc="record read hits and manage device residency as a "
                "cache tier"),
    Option("osd_hit_set_period", OPT_SECS, 2.0,
           desc="seconds of reads each hit-set interval covers"),
    Option("osd_hit_set_count", OPT_INT, 8,
           desc="archived hit-set intervals retained per PG"),
    Option("osd_hit_set_fpp", OPT_FLOAT, 0.05,
           desc="bloom hit-set target false-positive rate"),
    Option("osd_hit_set_target_size", OPT_INT, 128,
           desc="expected inserts a hit-set interval is sized for"),
    Option("osd_min_read_recency_for_promote", OPT_INT, 1,
           desc="consecutive newest hit sets an object must appear in "
                "before a read promotes it (0 = always)"),
    Option("osd_min_write_recency_for_promote", OPT_INT, 1,
           desc="consecutive newest hit sets an object must appear in "
                "before a write installs a resident (0 = always; the "
                "r10 behavior was an unconditional install)"),
    Option("osd_tier_pagestore", OPT_BOOL, True,
           desc="back the residency tier with the paged store "
                "(page table + ragged tails + dirty bits) instead of "
                "monolithic per-object buffers"),
    Option("osd_tier_page_bytes", OPT_SIZE, 64 << 10,
           desc="page size of the paged resident store (u32-word "
                "pages; eviction and dirty tracking are per page)"),
    Option("osd_tier_device_slab", OPT_BOOL, True,
           desc="allow the paged resident store's device arm "
                "(jax.Array sub-slabs, jitted in-place installs and "
                "gathers) when a real device backend is live; false "
                "pins the host-numpy arm. CEPH_TPU_DEVICE_SLAB=1/0 "
                "overrides in either direction"),
    Option("osd_tier_cache_mode", OPT_STR, "writethrough",
           desc="default cache mode for tiered pools (pool opt "
                "cache_mode overrides): writethrough applies local "
                "shards synchronously, writeback defers them to dirty "
                "pages flushed by the agent"),
    Option("osd_cache_min_size", OPT_INT, 2,
           desc="writeback fast-ack quorum: a put acks once the raw "
                "dirty object is committed on this many cache-tier "
                "processes (primary + min_size-1 acting peers); fewer "
                "live acting members falls back to synchronous "
                "writethrough for that op"),
    Option("osd_tier_slab_prewarm", OPT_BOOL, True,
           desc="compile the paged store's device-arm install/gather "
                "kernels for the configured page geometry (all pow2 row "
                "buckets) at store build, off the put path"),
    Option("osd_cache_target_dirty_ratio", OPT_FLOAT, 0.4,
           desc="agent flushes dirty pages when dirty bytes exceed "
                "this fraction of the tier target"),
    Option("osd_tier_flush_age", OPT_SECS, 5.0,
           desc="dirty residents older than this flush on the next "
                "agent pass regardless of the dirty ratio (0 = "
                "ratio/pressure-driven only)"),
    Option("osd_tier_full_target_factor", OPT_FLOAT, 0.5,
           desc="fullness pressure: NEARFULL or worse on the backing "
                "store scales the tier's effective target by this "
                "factor (and forces dirty flush ahead of eviction)"),
    Option("osd_tier_promote_max_objects_sec", OPT_INT, 32,
           desc="promotion rate ceiling, objects/sec (0 = unthrottled)"),
    Option("osd_tier_promote_max_bytes_sec", OPT_SIZE, 64 << 20,
           desc="promotion rate ceiling, bytes/sec (0 = unthrottled)"),
    Option("osd_tier_target_max_bytes", OPT_SIZE, 0,
           desc="resident byte budget the tier agent enforces "
                "(0 = the planar store's capacity)"),
    Option("osd_cache_target_full_ratio", OPT_FLOAT, 0.8,
           desc="agent evicts when resident bytes exceed this fraction "
                "of the target"),
    Option("osd_tier_agent_interval", OPT_SECS, 0.5,
           desc="tier agent due-scan cadence (0 disables the agent)"),
    # the one name the OSD actually reads (the old *_probability/
    # *_duration pair was never consumed — a lint dead-option finding):
    # seconds every BatchingQueue device dispatch sleeps, aging in-flight
    # ops past the SLOW_OPS complaint threshold in CI
    Option("osd_debug_inject_dispatch_delay", OPT_SECS, 0.0,
           level=LEVEL_DEV),
    # capacity / fullness plane (reference mon_osd_nearfull_ratio /
    # backfillfull / full ratios in the OSDMap + osd_failsafe_full_ratio;
    # the mon derives per-OSD NEARFULL/BACKFILLFULL/FULL states from the
    # statfs piggybacked on liveness pings)
    Option("osd_store_capacity_bytes", OPT_SIZE, 0,
           desc="byte ceiling every object store reports via statfs "
                "(0 = unlimited, the pre-capacity behavior); "
                "vstart seeds each OSD's store from it"),
    Option("osd_failsafe_full_ratio", OPT_FLOAT, 0.97,
           desc="last-resort store guard: a write that would push used "
                "bytes past this fraction of capacity is refused with a "
                "typed ENOSPC BEFORE anything mutates"),
    Option("mon_osd_nearfull_ratio", OPT_FLOAT, 0.85,
           desc="default nearfull ratio seeded into new OSDMaps "
                "(`ceph osd set-nearfull-ratio` overrides live)"),
    Option("mon_osd_backfillfull_ratio", OPT_FLOAT, 0.90,
           desc="default backfillfull ratio seeded into new OSDMaps "
                "(backfill reservations refuse onto OSDs past it)"),
    Option("mon_osd_full_ratio", OPT_FLOAT, 0.95,
           desc="default full ratio seeded into new OSDMaps (writes to "
                "PGs with a FULL acting member fail typed ENOSPC; "
                "deletes are exempt)"),
    Option("mon_osd_full_hysteresis", OPT_FLOAT, 0.01,
           desc="utilization must drop this far below a fullness "
                "threshold before the mon auto-clears the state "
                "(flap damping on the ping cadence)"),
    Option("osd_backfill_toofull_retry", OPT_SECS, 1.0,
           desc="retry cadence for a backfill parked on a BACKFILLFULL "
                "target (resumes when the target frees space)"),
    Option("osd_debug_inject_full", OPT_STR, "", level=LEVEL_DEV,
           desc="force reported utilization: 'RATIO' (this OSD) or "
                "'ID:RATIO[,ID:RATIO...]' — drives the fullness ladder "
                "in CI without writing gigabytes "
                "(CEPH_TPU_INJECT_FULL env equivalent)"),
    # objectstore
    Option("bluestore_csum_type", OPT_STR, "crc32c",
           enum_values=("none", "crc32c")),
    Option("bluestore_debug_inject_read_err", OPT_BOOL, False, level=LEVEL_DEV),
    Option("bluestore_debug_inject_csum_err_probability", OPT_FLOAT, 0.0,
           level=LEVEL_DEV),
    Option("bluestore_prefer_deferred_size", OPT_SIZE, 32768),
    # on-disk compression (reference bluestore_compression_* options;
    # per-pool compression_* opts override these store-wide defaults)
    Option("bluestore_compression_mode", OPT_STR, "none",
           enum_values=("none", "passive", "aggressive", "force")),
    Option("bluestore_compression_algorithm", OPT_STR, "zlib"),
    Option("bluestore_compression_min_blob_size", OPT_SIZE, 4096),
    Option("bluestore_compression_required_ratio", OPT_FLOAT, 0.875,
           desc="keep the compressed blob only when it shrinks to at "
                "most this fraction of the raw bytes"),
    # mon
    Option("mon_lease", OPT_SECS, 5.0),
    Option("mon_election_timeout", OPT_SECS, 1.0),
    # logging (src/common/dout.h per-subsys levels; all RUNTIME-mutable —
    # `ceph tell <daemon> config set debug_ms 10` / asok `config set` is
    # the live-diagnosis workflow, the Log level cache invalidates via a
    # debug_* observer)
    Option("log_max_recent", OPT_INT, 500),
    Option("debug_osd", OPT_INT, 1, level=LEVEL_DEV),
    Option("debug_mon", OPT_INT, 1, level=LEVEL_DEV),
    Option("debug_ms", OPT_INT, 0, level=LEVEL_DEV),
    Option("debug_ec", OPT_INT, 1, level=LEVEL_DEV),
    Option("debug_bluestore", OPT_INT, 1, level=LEVEL_DEV),
    Option("debug_client", OPT_INT, 1, level=LEVEL_DEV),
    Option("debug_clog", OPT_INT, 1, level=LEVEL_DEV,
           desc="local-log mirror level of cluster-log entries"),
    # cluster log + crash telemetry (reference mon_cluster_log_*,
    # mon_client_log_interval, mgr/crash warn_recent_interval)
    Option("mon_cluster_log_entries", OPT_INT, 500,
           desc="cluster-log tail the mon retains (paxos-replicated; "
                "`ceph log last` serves from it)"),
    Option("mon_client_log_interval", OPT_SECS, 0.25,
           desc="LogClient flush cadence; errors flush immediately"),
    Option("clog_max_pending", OPT_INT, 2048,
           desc="unacked cluster-log entries a daemon holds before "
                "dropping oldest (drop count kept)"),
    Option("mon_crash_warn_age", OPT_SECS, 14 * 24 * 3600.0,
           desc="unarchived crashes newer than this raise RECENT_CRASH"),
    Option("mon_crash_max", OPT_INT, 64,
           desc="crash reports the mon retains (oldest pruned)"),
    Option("mon_crash_recent_max_bytes", OPT_SIZE, 32 << 10,
           desc="per-crash dump_recent ring byte budget in the mon's "
                "registry (newest entries kept; the registry rides "
                "every paxos snapshot)"),
    Option("crash_dir", OPT_STR, "", flags=(FLAG_STARTUP,),
           desc="spool dir for crash reports the mon could not take "
                "(replayed at next boot); empty disables spooling"),
    Option("osd_debug_inject_crash", OPT_BOOL, False, level=LEVEL_DEV,
           desc="raise a fatal exception in the OSD's next ping tick "
                "(crash-telemetry CI gate)"),
)


class Config:
    """Layered, observable, typed config (ConfigProxy role).

    Unknown keys are accepted as untyped passthrough values so subsystem
    experiments don't need schema edits first (the reference requires
    declarations; we degrade to OPT_STR-like behavior and flag them in
    ``show()``).
    """

    # source priorities, low to high (mon-centralized beats file, CLI beats all)
    SOURCES = ("default", "file", "env", "mon", "override", "cli")

    def __init__(self, values: Optional[Dict[str, Any]] = None,
                 schema: Optional[Dict[str, Option]] = None):
        self.schema: Dict[str, Option] = dict(schema or DEFAULT_SCHEMA)
        self._sources: Dict[str, Dict[str, Any]] = {s: {} for s in self.SOURCES}
        self._observers: List[Tuple[Callable, Tuple[str, ...]]] = []
        self._started = False
        if values:
            self.set_source("override", values)

    # -- resolution ----------------------------------------------------------

    def get(self, name: str, default: Any = None) -> Any:
        opt = self.schema.get(name)
        for source in reversed(self.SOURCES):
            if name in self._sources[source]:
                raw = self._sources[source][name]
                return opt.parse(raw) if opt else raw
        if opt is not None:
            return opt.default
        return default

    def __contains__(self, name: str) -> bool:
        return any(name in vals for vals in self._sources.values()) or name in self.schema

    def show(self) -> Dict[str, Any]:
        """Effective values for every known + set key, schema'd or not."""
        names: Set[str] = set(self.schema)
        for vals in self._sources.values():
            names |= set(vals)
        return {n: self.get(n) for n in sorted(names)}

    def diff(self) -> Dict[str, Any]:
        """Keys whose effective value differs from the schema default."""
        out = {}
        for name, value in self.show().items():
            opt = self.schema.get(name)
            if opt is None or value != opt.default:
                out[name] = value
        return out

    # -- mutation ------------------------------------------------------------

    def mark_started(self) -> None:
        """Daemon finished global_init: startup-flagged options freeze."""
        self._started = True

    def set(self, name: str, value: Any, source: str = "cli") -> None:
        opt = self.schema.get(name)
        if opt is not None:
            if self._started and FLAG_STARTUP in opt.flags:
                raise ValueError(f"{name} can only be set at daemon startup")
            opt.parse(value)  # validate eagerly
        old = self.get(name)
        self._sources[source][name] = value
        if self.get(name) != old:
            self._notify({name})

    def rm(self, name: str, source: str = "cli") -> None:
        old = self.get(name)
        self._sources[source].pop(name, None)
        if self.get(name) != old:
            self._notify({name})

    def set_source(self, source: str, values: Dict[str, Any]) -> None:
        """Install/replace a whole source layer (e.g. a mon config epoch).
        Values are validated BEFORE the swap so a bad pushed value can't
        poison the layer."""
        if source not in self._sources:
            raise ValueError(f"unknown config source {source}")
        for k, v in values.items():
            opt = self.schema.get(k)
            if opt is not None:
                opt.parse(v)
        before = {k: self.get(k) for k in set(self._sources[source]) | set(values)}
        self._sources[source] = dict(values)
        changed = {k for k, v in before.items() if self.get(k) != v}
        if changed:
            self._notify(changed)

    # -- observers -----------------------------------------------------------

    def add_observer(self, handler: Callable[["Config", Set[str]], None],
                     keys: Iterable[str]) -> None:
        self._observers.append((handler, tuple(keys)))

    def remove_observer(self, handler: Callable) -> None:
        self._observers = [(h, k) for h, k in self._observers if h is not handler]

    def _notify(self, changed: Set[str]) -> None:
        for handler, keys in list(self._observers):
            hit = changed & set(keys)
            # a trailing-* key subscribes to a PREFIX (the debug_* family:
            # per-subsystem level options are open-ended, and the log's
            # level cache must invalidate on any of them)
            for k in keys:
                if k.endswith("*"):
                    hit |= {c for c in changed if c.startswith(k[:-1])}
            if hit:
                handler(self, hit)

    # -- parsing helpers -----------------------------------------------------

    @classmethod
    def from_conf_file(cls, text: str) -> "Config":
        """Parse a minimal ceph.conf-style ini (global section only for now)."""
        cfg = cls()
        values: Dict[str, Any] = {}
        for line in text.splitlines():
            line = line.split("#", 1)[0].split(";", 1)[0].strip()
            if not line or line.startswith("["):
                continue
            if "=" in line:
                k, v = line.split("=", 1)
                values[k.strip().replace(" ", "_")] = v.strip()
        cfg.set_source("file", values)
        return cfg
