"""Perf counters: cheap in-process metrics with admin-socket dumps.

Role-equivalent of the reference's PerfCounters/PerfCountersCollection
(reference src/common/perf_counters.cc): a daemon builds named counter sets
(PerfCountersBuilder), bumps them on the hot path (inc/dec/set/tinc/hinc),
and operators read them via ``perf dump`` on the admin socket and via the
mgr's prometheus exporter.  Three kinds mirror the reference:

- u64 counters/gauges (PERFCOUNTER_U64)
- time/long-run averages: (sum, count) pairs dumped as avgcount+sum
  (PERFCOUNTER_LONGRUNAVG — l_osd_op_lat style, src/osd/osd_perf_counters.cc:49)
- 2D histograms of (value, count) power-of-2 buckets (PERFCOUNTER_HISTOGRAM)
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

U64 = "u64"
LONGRUNAVG = "longrunavg"
HISTOGRAM = "histogram"


class _Counter:
    __slots__ = ("name", "kind", "desc", "value", "sum", "count", "buckets")

    def __init__(self, name: str, kind: str, desc: str):
        self.name = name
        self.kind = kind
        self.desc = desc
        self.value = 0
        self.sum = 0.0
        self.count = 0
        self.buckets: Optional[List[int]] = [0] * 32 if kind == HISTOGRAM else None


class PerfCounters:
    """One named set of counters (e.g. 'osd', 'ec_tpu', 'messenger')."""

    def __init__(self, name: str):
        self.name = name
        self._counters: Dict[str, _Counter] = {}
        self._lock = threading.Lock()
        # optional owner callback invoked after reset(): gauge-style
        # counters (cache entries, resident bytes) mirror LIVE state that
        # zeroing misreports until the next mutation — the owner re-sets
        # them here so `perf reset` restarts rates without lying gauges
        self.resync: Optional[Any] = None
        # optional owner callback invoked BEFORE dump(): counters whose
        # source of truth lives outside this process (the reactor worker
        # processes' shared-memory blocks) refresh here so every dump
        # reports the whole plane without a polling loop
        self.presample: Optional[Any] = None

    # -- hot path ------------------------------------------------------------

    def inc(self, name: str, amount: int = 1) -> None:
        c = self._counters[name]
        with self._lock:
            c.value += amount

    def dec(self, name: str, amount: int = 1) -> None:
        c = self._counters[name]
        with self._lock:
            c.value -= amount

    def set(self, name: str, value: int) -> None:
        c = self._counters[name]
        with self._lock:
            c.value = value

    def tinc(self, name: str, seconds: float) -> None:
        """Add one latency observation to a longrunavg."""
        c = self._counters[name]
        with self._lock:
            c.sum += seconds
            c.count += 1

    @contextlib.contextmanager
    def time_avg(self, name: str):
        """Time a block into a longrunavg — ``with pc.time_avg("op_lat"):``
        instead of hand-rolled time.monotonic() pairs at every call site.
        The observation is recorded even when the block raises (a failed
        op still spent the time)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.tinc(name, time.monotonic() - t0)

    def ensure(self, name: str, kind: str = U64,
               desc: str = "") -> None:
        """Declare a counter after build time (dynamic families, e.g. the
        messenger's per-message-type counts).  Idempotent; thread-safe
        against dump()."""
        if name in self._counters:
            return
        with self._lock:
            if name not in self._counters:
                self._counters[name] = _Counter(name, kind, desc)

    def hinc(self, name: str, value: float) -> None:
        """Add an observation to a power-of-2-bucketed histogram."""
        c = self._counters[name]
        v = int(value)
        bucket = 0 if v <= 0 else min(31, v.bit_length())
        with self._lock:
            c.buckets[bucket] += 1
            c.count += 1
            c.sum += value

    def get(self, name: str) -> Any:
        c = self._counters[name]
        if c.kind == U64:
            return c.value
        if c.kind == LONGRUNAVG:
            return (c.count, c.sum)
        return list(c.buckets)

    def avg(self, name: str) -> float:
        c = self._counters[name]
        return c.sum / c.count if c.count else 0.0

    def reset(self) -> None:
        """Zero every counter in the set (the `perf reset` admin command):
        tests and bench warmup/timed windows isolate measurement intervals
        instead of diffing snapshots by hand."""
        with self._lock:
            for c in self._counters.values():
                c.value = 0
                c.sum = 0.0
                c.count = 0
                if c.buckets is not None:
                    c.buckets = [0] * len(c.buckets)
        if self.resync is not None:
            try:
                self.resync()  # outside the lock: resync calls set()
            except Exception:
                pass

    # -- dump ----------------------------------------------------------------

    def dump(self) -> Dict[str, Any]:
        if self.presample is not None:
            try:
                self.presample()  # outside the lock: presample calls set()
            except Exception:
                pass
        out: Dict[str, Any] = {}
        # snapshot under the lock: ensure() may add counters concurrently
        with self._lock:
            counters = list(self._counters.values())
        for c in counters:
            if c.kind == U64:
                out[c.name] = c.value
            elif c.kind == LONGRUNAVG:
                out[c.name] = {"avgcount": c.count, "sum": c.sum}
            else:
                out[c.name] = {
                    "count": c.count,
                    "sum": c.sum,
                    "buckets": list(c.buckets),
                }
        return out

    def schema(self) -> Dict[str, Dict[str, str]]:
        # snapshot under the lock, same ensure() race as dump()
        with self._lock:
            counters = list(self._counters.values())
        return {c.name: {"type": c.kind, "description": c.desc}
                for c in counters}


class PerfCountersBuilder:
    """Declare-then-build, as the reference does (add_u64_counter/add_time_avg)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, U64, desc)
        return self

    def add_u64_counter(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        return self.add_u64(name, desc)

    def add_time_avg(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, LONGRUNAVG, desc)
        return self

    def add_histogram(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, HISTOGRAM, desc)
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """All counter sets of one daemon; the admin socket dumps this."""

    def __init__(self):
        self._sets: Dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> PerfCounters:
        with self._lock:
            self._sets[pc.name] = pc
        return pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._sets.pop(name, None)

    def get(self, name: str) -> Optional[PerfCounters]:
        return self._sets.get(name)

    def dump(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {name: pc.dump() for name, pc in self._sets.items()}

    def reset(self, name: Optional[str] = None) -> List[str]:
        """Zero one named set, or every set when name is None/"all".
        Returns the names of the sets that were reset."""
        with self._lock:
            if name and name != "all":
                targets = [self._sets[name]] if name in self._sets else []
            else:
                targets = list(self._sets.values())
        for pc in targets:
            pc.reset()
        return [pc.name for pc in targets]

    def schema(self) -> Dict[str, Any]:
        with self._lock:
            return {name: pc.schema() for name, pc in self._sets.items()}
