"""In-flight op tracking with event timelines.

Role-equivalent of the reference's TrackedOp/OpTracker (reference
src/common/TrackedOp.h): every client op gets a TrackedOp at dispatch;
pipeline stages call ``mark_event`` ("queued_for_pg", "start ec write",
"commit_sent", ...); the admin socket serves ``dump_ops_in_flight`` and
``dump_historic_ops`` (a bounded ring of the slowest/most recent completed
ops) — the primary live-debugging tool for stuck I/O.  TrackedOp doubles as
the span carrier for the zipkin/jaeger-style trace annotations the EC write
path emits (reference ECBackend.cc:2027).
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Any, Deque, Dict, List, Optional

_seq = itertools.count(1)


class TrackedOp:
    __slots__ = ("tracker", "seq", "desc", "initiated_at", "events", "done_at")

    def __init__(self, tracker: "OpTracker", desc: str):
        self.tracker = tracker
        self.seq = next(_seq)
        self.desc = desc
        self.initiated_at = time.time()
        self.events: List[Dict[str, Any]] = []
        self.done_at: Optional[float] = None

    def mark_event(self, event: str) -> None:
        self.events.append({"time": time.time(), "event": event})

    def finish(self) -> None:
        if self.done_at is None:
            self.done_at = time.time()
            self.tracker._complete(self)

    @property
    def duration(self) -> float:
        return (self.done_at or time.time()) - self.initiated_at

    def dump(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "description": self.desc,
            "initiated_at": self.initiated_at,
            "age": self.duration,
            "done": self.done_at is not None,
            "type_data": {"events": list(self.events)},
        }


class OpTracker:
    def __init__(self, history_size: int = 20, history_slow_size: int = 20,
                 slow_threshold: float = 0.5):
        self._in_flight: Dict[int, TrackedOp] = {}
        self._history: Deque[TrackedOp] = collections.deque(maxlen=history_size)
        self._slow: Deque[TrackedOp] = collections.deque(maxlen=history_slow_size)
        self.slow_threshold = slow_threshold

    def create(self, desc: str) -> TrackedOp:
        op = TrackedOp(self, desc)
        self._in_flight[op.seq] = op
        return op

    def _complete(self, op: TrackedOp) -> None:
        self._in_flight.pop(op.seq, None)
        self._history.append(op)
        if op.duration >= self.slow_threshold:
            self._slow.append(op)

    def dump_ops_in_flight(self) -> Dict[str, Any]:
        ops = [op.dump() for op in self._in_flight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> Dict[str, Any]:
        ops = [op.dump() for op in self._history]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_slow_ops(self) -> Dict[str, Any]:
        ops = [op.dump() for op in self._slow]
        return {"num_ops": len(ops), "ops": ops}

    def register_asok(self, asok) -> None:
        asok.register("dump_ops_in_flight", lambda a: self.dump_ops_in_flight(),
                      "in-flight ops with event timelines")
        asok.register("dump_historic_ops", lambda a: self.dump_historic_ops(),
                      "recently completed ops")
        asok.register("dump_historic_slow_ops", lambda a: self.dump_historic_slow_ops(),
                      "recent slow ops")
