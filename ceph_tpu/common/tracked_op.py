"""In-flight op tracking with event timelines.

Role-equivalent of the reference's TrackedOp/OpTracker (reference
src/common/TrackedOp.h): every client op AND every OSD-side op — EC
sub-writes, recovery pushes, tier promotions, the evict agent — gets a
TrackedOp at dispatch; pipeline stages call ``mark_event`` with names
from the shared EVENT VOCABULARY below; the admin socket serves
``dump_ops_in_flight``, ``dump_historic_ops`` (a bounded ring of
recently completed ops) and ``dump_historic_slow_ops`` (ops that aged
past the complaint threshold) — the primary live-debugging tool for
stuck I/O.  TrackedOp carries the op's trace span (``trace``), so the
timeline and the cross-daemon span tree name the same op.

Event vocabulary (client-op timelines; sub-ops use a compact subset):

    initiated             op record created (implicit: initiated_at)
    queued_for_pg         entered the sharded op queue
    reached_pg            dequeued; the PG handler is running
    backoff               dropped-and-blocked (MOSDBackoff sent)
    rmw_read              partial-overwrite base read started
    ec_encode_dispatched  encode submitted to the device queue
    encoded               encode results in hand
    sub_writes_sent       the k+m fan-out is on the wire
    waiting_for_subops    parked gathering sub-write acks
    commit_gathered       quorum of sub-write acks arrived
    decode_dispatched     (reads) decode submitted to the device queue
    decoded               (reads) decode results in hand
    commit_sent           reply handed to the client connection
    done                  finish() (implicit: done_at)

Per-phase latencies: on completion the tracker turns adjacent event
pairs into named phases (``PHASES``) and feeds the ``optracker`` perf
set (one longrunavg + one power-of-2 µs histogram per phase) plus a
bounded raw-sample ring that ``phase_percentiles()`` reduces to
p50/p99/p999 — the numbers the BENCH record embeds.

Thread-safety: seq allocation is per-tracker, the in-flight map and
history rings mutate only under the tracker lock, and a single op's
event list is bounded (``max_events``) so a stuck op polled by a
watchdog cannot grow its timeline without bound.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder

# adjacent-event pairs -> phase name (per-phase latency accounting);
# both the write and read pipelines resolve to the same phase names so
# one schema serves `perf dump`, the BENCH record, and the tests
PHASES: Dict[tuple, str] = {
    ("queued_for_pg", "reached_pg"): "queue_wait",
    ("ec_encode_dispatched", "encoded"): "ec_dispatch",
    ("decode_dispatched", "decoded"): "ec_dispatch",
    # the write path marks waiting_for_subops right after
    # sub_writes_sent, so the gather window is measured from there
    ("waiting_for_subops", "commit_gathered"): "subop_wait",
    # reads: sub-read fan-out + gather, the read-side analog
    ("sub_reads_sent", "decode_dispatched"): "subop_wait",
}

PHASE_NAMES = ("queue_wait", "ec_dispatch", "subop_wait")


def build_optracker_perf() -> PerfCounters:
    """The `optracker` counter set — one per daemon Context, carried by
    `perf dump` / mgr /metrics.  Schema:

      op_created / op_done   u64         tracked ops created / completed
      slow_ops_observed      u64         completions past the complaint
                                         threshold
      events_dropped         u64         mark_event calls absorbed by the
                                         per-op event bound
      inflight               u64         ops currently tracked (gauge)
      op_lat                 longrunavg  whole-op seconds
      lat_<phase>            longrunavg  per-phase seconds
      hist_<phase>_us        histogram   per-phase µs (power-of-2)
    """
    b = PerfCountersBuilder("optracker")
    b.add_u64_counter("op_created", "tracked ops created")
    b.add_u64_counter("op_done", "tracked ops completed")
    b.add_u64_counter("slow_ops_observed",
                      "completions past the complaint threshold")
    b.add_u64_counter("events_dropped",
                      "mark_event calls absorbed by the per-op bound")
    b.add_u64("inflight", "ops currently tracked (gauge)")
    b.add_time_avg("op_lat", "whole-op seconds")
    for phase in PHASE_NAMES:
        b.add_time_avg(f"lat_{phase}", f"{phase} seconds per op")
        b.add_histogram(f"hist_{phase}_us", f"{phase} microseconds")
    return b.create_perf_counters()


def percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile over raw samples (q in [0, 1])."""
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def percentile_summary(samples: List[float]) -> Dict[str, float]:
    """{p50_us, p99_us, p999_us, count} over raw SECONDS samples — the
    one reduction behind phase_percentiles and the BENCH record (bench
    merges samples across OSDs first, then calls this)."""
    return {"p50_us": round(percentile(samples, 0.50) * 1e6, 1),
            "p99_us": round(percentile(samples, 0.99) * 1e6, 1),
            "p999_us": round(percentile(samples, 0.999) * 1e6, 1),
            "count": len(samples)}


class TrackedOp:
    __slots__ = ("tracker", "seq", "desc", "reqid", "initiated_at",
                 "events", "done_at", "trace", "complaint_ok", "_dropped",
                 "qos_tag")

    def __init__(self, tracker: "OpTracker", desc: str, reqid: str = "",
                 trace: Any = None):
        self.tracker = tracker
        self.seq = tracker._next_seq()
        self.desc = desc
        self.reqid = reqid
        self.initiated_at = time.time()
        self.events: List[Dict[str, Any]] = []
        self.done_at: Optional[float] = None
        # the op's trace span (tracing.Span), when one is attached: the
        # timeline and the span tree name the same op
        self.trace = trace
        # complaint_ok=False exempts the op from slow-op aging: ops that
        # LEGITIMATELY park for seconds (a notify gathering watcher
        # acks) must not raise SLOW_OPS on a healthy cluster
        self.complaint_ok = True
        self._dropped = 0
        # tenant-class tag (qos.tenant_class of the op's client): when
        # set, phase samples ALSO land in a per-class ring keyed
        # "cls:<tag>|<phase>" — the per-tenant-class percentile path the
        # macro bench reduces ("" = untagged, no extra ring)
        self.qos_tag = ""

    def mark_event(self, event: str) -> None:
        # bounded: a stuck op re-marked by a poller must not grow its
        # timeline without bound (the reference caps events per op too)
        if len(self.events) >= self.tracker.max_events:
            self._dropped += 1
            self.tracker.perf.inc("events_dropped")
            return
        self.events.append({"time": time.time(), "event": event})

    def finish(self) -> None:
        if self.done_at is None:
            self.done_at = time.time()
            self.tracker._complete(self)

    @property
    def duration(self) -> float:
        return (self.done_at or time.time()) - self.initiated_at

    def phase_latencies(self) -> Dict[str, float]:
        """Adjacent-event-pair phases (PHASES) -> seconds."""
        out: Dict[str, float] = {}
        prev_name, prev_t = "initiated", self.initiated_at
        for ev in self.events:
            phase = PHASES.get((prev_name, ev["event"]))
            if phase is not None:
                out[phase] = ev["time"] - prev_t
            prev_name, prev_t = ev["event"], ev["time"]
        return out

    def dump(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "seq": self.seq,
            "description": self.desc,
            "initiated_at": self.initiated_at,
            "age": self.duration,
            "done": self.done_at is not None,
            "type_data": {"events": list(self.events)},
        }
        if self.reqid:
            d["reqid"] = self.reqid
        if self.trace is not None:
            d["trace_id"] = self.trace.trace_id
            d["span_id"] = self.trace.span_id
        if self._dropped:
            d["events_dropped"] = self._dropped
        return d


class OpTracker:
    """Thread-safe op tracker: one per daemon Context.

    ``slow_threshold`` is the complaint age (reference
    osd_op_complaint_time): completed ops that took at least this long
    join the slow ring; in-flight ops older than it surface through
    ``slow_op_summary`` (the SLOW_OPS health feed)."""

    SAMPLE_RING = 2048  # raw per-phase samples kept for percentiles
    # bound on DISTINCT sample-ring keys: the per-class keys derive from
    # the wire-controlled client name, so without a cap a sender minting
    # a fresh tenant class per op would grow a new ring forever; at the
    # cap, samples for NEW tagged keys are dropped (untagged phase rings
    # are few and always created first)
    MAX_SAMPLE_KEYS = 256

    def __init__(self, history_size: int = 20, history_slow_size: int = 20,
                 slow_threshold: float = 2.0, max_events: int = 128,
                 perf: Optional[PerfCounters] = None):
        self._lock = threading.Lock()
        self._seq = itertools.count(1)  # per-tracker, allocated under lock
        self._in_flight: Dict[int, TrackedOp] = {}
        self._history: Deque[TrackedOp] = collections.deque(maxlen=history_size)
        self._slow: Deque[TrackedOp] = collections.deque(maxlen=history_slow_size)
        self.slow_threshold = slow_threshold
        self.max_events = max_events
        self.perf = perf if perf is not None else build_optracker_perf()
        self._samples: Dict[str, Deque[float]] = {}

    def _next_seq(self) -> int:
        with self._lock:
            return next(self._seq)

    def create(self, desc: str, reqid: str = "",
               trace: Any = None) -> TrackedOp:
        op = TrackedOp(self, desc, reqid=reqid, trace=trace)
        with self._lock:
            self._in_flight[op.seq] = op
            # gauge published under the tracker lock: a set() outside it
            # can lose the race with a concurrent create/complete and
            # leave a stale inflight count until the next op
            self.perf.set("inflight", len(self._in_flight))
        self.perf.inc("op_created")
        return op

    def _complete(self, op: TrackedOp) -> None:
        slow = op.complaint_ok and op.duration >= self.slow_threshold
        with self._lock:
            self._in_flight.pop(op.seq, None)
            self._history.append(op)
            if slow:
                self._slow.append(op)
            self.perf.set("inflight", len(self._in_flight))
        self.perf.inc("op_done")
        self.perf.tinc("op_lat", op.duration)
        if slow:
            self.perf.inc("slow_ops_observed")
        for phase, dt in op.phase_latencies().items():
            self.perf.tinc(f"lat_{phase}", dt)
            self.perf.hinc(f"hist_{phase}_us", dt * 1e6)
            keys = (phase,) if not op.qos_tag \
                else (phase, f"cls:{op.qos_tag}|{phase}")
            with self._lock:
                for key in keys:
                    ring = self._samples.get(key)
                    if ring is None:
                        if len(self._samples) >= self.MAX_SAMPLE_KEYS:
                            continue  # key-cardinality bound (see above)
                        ring = self._samples[key] = collections.deque(
                            maxlen=self.SAMPLE_RING)
                    ring.append(dt)

    # -- percentiles ---------------------------------------------------------

    def phase_samples(self) -> Dict[str, List[float]]:
        with self._lock:
            return {k: list(v) for k, v in self._samples.items()}

    def clear_samples(self) -> None:
        with self._lock:
            self._samples.clear()

    def phase_percentiles(self) -> Dict[str, Dict[str, float]]:
        """{phase: {p50, p99, p999, count}} in MICROSECONDS — the shape
        the BENCH record embeds."""
        return {phase: percentile_summary(samples)
                for phase, samples in self.phase_samples().items()}

    # -- slow-op health feed -------------------------------------------------

    def slow_op_summary(self, complaint: Optional[float] = None) -> Dict[str, Any]:
        """In-flight ops older than the complaint threshold — what the
        SLOW_OPS health check reports (count + oldest age + samples)."""
        threshold = self.slow_threshold if complaint is None else complaint
        now = time.time()
        with self._lock:
            aged = [op for op in self._in_flight.values()
                    if op.complaint_ok
                    and now - op.initiated_at >= threshold]
        aged.sort(key=lambda o: o.initiated_at)
        return {
            "count": len(aged),
            "oldest_age": round(now - aged[0].initiated_at, 3) if aged else 0.0,
            "complaint_time": threshold,
            "ops": [{"description": op.desc,
                     "age": round(now - op.initiated_at, 3),
                     "last_event": op.events[-1]["event"] if op.events
                     else "initiated"}
                    for op in aged[:8]],
        }

    # -- dumps ---------------------------------------------------------------

    def dump_ops_in_flight(self) -> Dict[str, Any]:
        with self._lock:
            ops = list(self._in_flight.values())
        dumped = [op.dump() for op in ops]
        return {"num_ops": len(dumped), "ops": dumped}

    def dump_historic_ops(self) -> Dict[str, Any]:
        with self._lock:
            ops = list(self._history)
        dumped = [op.dump() for op in ops]
        return {"num_ops": len(dumped), "ops": dumped}

    def dump_historic_slow_ops(self) -> Dict[str, Any]:
        with self._lock:
            ops = list(self._slow)
        dumped = [op.dump() for op in ops]
        return {"num_ops": len(dumped),
                "complaint_time": self.slow_threshold,
                "ops": dumped}

    def register_asok(self, asok) -> None:
        asok.register("dump_ops_in_flight", lambda a: self.dump_ops_in_flight(),
                      "in-flight ops with event timelines")
        asok.register("dump_historic_ops", lambda a: self.dump_historic_ops(),
                      "recently completed ops")
        asok.register("dump_historic_slow_ops",
                      lambda a: self.dump_historic_slow_ops(),
                      "recent ops slower than the complaint threshold")
