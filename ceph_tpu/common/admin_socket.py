"""Admin socket: per-daemon unix-socket command server.

Role-equivalent of the reference's AdminSocket (reference
src/common/admin_socket.cc): each daemon exposes a ``.asok`` unix socket;
clients send a JSON request ``{"prefix": "<command>", ...args}`` terminated
by newline and receive a JSON reply.  Subsystems register hooks at runtime;
the always-present core commands mirror the reference's: ``help``,
``version``, ``perf dump``, ``perf schema``, ``config show``, ``config
set``, ``config diff``, ``log flush``, ``log dump``, ``dump_historic_ops``
/ ``dump_ops_in_flight`` (via the OpTracker hook, src/common/TrackedOp.h).
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Callable, Dict, Optional


class AdminSocket:
    def __init__(self, ctx, path: Optional[str] = None):
        self.ctx = ctx
        self.path = path
        self._hooks: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self._help: Dict[str, str] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.register("help", lambda a: dict(self._help), "list commands")
        self.register("version", lambda a: {"version": self.ctx.version},
                      "framework version")
        self.register("perf dump", lambda a: self.ctx.perf.dump(),
                      "dump perf counters")
        self.register("perf schema", lambda a: self.ctx.perf.schema(),
                      "dump perf counter schema")
        self.register("perf reset", self._perf_reset,
                      "zero counters in one set (name=<set>) or all sets")
        self.register("config show", lambda a: self.ctx.conf.show(),
                      "effective config")
        self.register("config diff", lambda a: self.ctx.conf.diff(),
                      "config vs defaults")
        self.register("config set", self._config_set, "set a runtime option")
        self.register("config get", lambda a: {a["key"]: self.ctx.conf.get(a["key"])},
                      "get one option")
        self.register("log flush", self._log_flush, "drain async log writes")
        self.register("log dump", self._log_dump, "dump in-memory log ring")
        # reference command name (`ceph daemon X log dump_recent`): same
        # ring, including the separately pinned error entries
        self.register("log dump_recent", self._log_dump,
                      "dump in-memory log ring (alias of log dump)")

    # -- hooks ---------------------------------------------------------------

    def register(self, prefix: str, hook: Callable[[Dict[str, Any]], Any],
                 help_text: str = "") -> None:
        self._hooks[prefix] = hook
        self._help[prefix] = help_text

    def unregister(self, prefix: str) -> None:
        self._hooks.pop(prefix, None)
        self._help.pop(prefix, None)

    def _perf_reset(self, args: Dict[str, Any]) -> Dict[str, Any]:
        reset = self.ctx.perf.reset(args.get("name", "all"))
        return {"success": bool(reset), "reset": reset}

    def _config_set(self, args: Dict[str, Any]) -> Dict[str, Any]:
        self.ctx.conf.set(args["key"], args["value"], source="cli")
        return {"success": True, "key": args["key"], "value": self.ctx.conf.get(args["key"])}

    def _log_flush(self, args: Dict[str, Any]) -> Dict[str, Any]:
        self.ctx.log.flush()
        return {"success": True}

    def _log_dump(self, args: Dict[str, Any]) -> Any:
        return [
            {"stamp": e[0], "subsys": e[1], "level": e[2], "message": e[3]}
            for e in self.ctx.log.dump_recent()
        ]

    # -- direct (in-process) execution --------------------------------------

    def execute(self, prefix: str, **args: Any) -> Any:
        """Run one hook.  May return an AWAITABLE when the hook is an
        async def (long-running commands like `pg scrub`): async
        callers (the unix-socket server, the MCommand tell handlers)
        await it; sync callers get the coroutine and must drive it."""
        hook = self._hooks.get(prefix)
        if hook is None:
            raise KeyError(f"unknown admin command {prefix!r}")
        return hook(args)

    async def execute_async(self, prefix: str, **args: Any) -> Any:
        """execute(), with awaitable results awaited — the one call
        async transports (asok server, MCommand) should use."""
        import inspect

        result = self.execute(prefix, **args)
        if inspect.isawaitable(result):
            result = await result
        return result

    # -- unix socket server --------------------------------------------------

    async def start(self, path: Optional[str] = None) -> str:
        self.path = path or self.path
        if self.path is None:
            raise ValueError("admin socket path not set")
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._server = await asyncio.start_unix_server(self._serve, path=self.path)
        return self.path

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
            self._server = None
        if self.path and os.path.exists(self.path):
            try:
                os.unlink(self.path)
            except OSError:
                pass

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    prefix = req.pop("prefix")
                    result = await self.execute_async(prefix, **req)
                    reply = {"ok": True, "result": result}
                except Exception as e:  # command errors go back to the caller
                    reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                writer.write(json.dumps(reply, default=repr).encode() + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()


async def asok_command(path: str, prefix: str, **args: Any) -> Any:
    """Client helper: one command against a daemon's admin socket
    (the `ceph daemon <name> <cmd>` role)."""
    reader, writer = await asyncio.open_unix_connection(path)
    try:
        req = {"prefix": prefix, **args}
        writer.write(json.dumps(req).encode() + b"\n")
        await writer.drain()
        line = await reader.readline()
        reply = json.loads(line)
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", "admin command failed"))
        return reply["result"]
    finally:
        writer.close()
