"""Per-daemon service bundle (CephContext role).

Role-equivalent of the reference's CephContext (reference
src/common/ceph_context.cc): one object owning the config proxy, perf
counter collection, log, admin socket, and op tracker, created by
``global_init()``-equivalent daemon setup and threaded through every
subsystem.  Daemons that predate this layer pass plain dicts as conf; the
Context accepts those and wraps them.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

from ceph_tpu.common.admin_socket import AdminSocket
from ceph_tpu.common.config import Config
from ceph_tpu.common.log import Log
from ceph_tpu.common.perf_counters import PerfCountersCollection
from ceph_tpu.common.tracing import Tracer
from ceph_tpu.common.tracked_op import OpTracker

VERSION = "1.0.0-tpu"


class Context:
    def __init__(self, name: str = "client",
                 conf: Optional[Union[Config, Dict[str, Any]]] = None,
                 log_sink=None):
        if isinstance(conf, Config):
            self.conf = conf
        else:
            self.conf = Config(conf or {})
        self.name = name
        self.version = VERSION
        self.perf = PerfCountersCollection()
        self.log = Log(self.conf, sink=log_sink, name=name)
        self.asok = AdminSocket(self)
        # op tracker sized/thresholded by config (reference
        # osd_op_complaint_time / osd_op_history_size); its `optracker`
        # perf set joins the daemon collection so per-phase latencies
        # ride `perf dump` and the mgr exporter
        self.op_tracker = OpTracker(
            history_size=int(self.conf.get("osd_op_history_size", 64) or 64),
            history_slow_size=int(
                self.conf.get("osd_op_history_slow_size", 64) or 64),
            slow_threshold=float(
                self.conf.get("osd_op_complaint_time", 2.0) or 2.0),
            max_events=int(
                self.conf.get("osd_op_tracker_max_events", 128) or 128))
        self.perf.add(self.op_tracker.perf)
        self.op_tracker.register_asok(self.asok)
        self.tracer = Tracer(service=name)
        self.tracer.register_asok(self.asok)
        # runtime debug levels: the Log caches per-subsystem levels (one
        # dict lookup per dout); any debug_* change — asok `config set`,
        # `ceph tell ... config set`, a mon-pushed layer — invalidates it
        self.conf.add_observer(self._on_debug_change,
                               ("debug_*", "log_max_recent"))

    def _on_debug_change(self, conf, changed) -> None:
        self.log.invalidate_levels()

    def dout(self, subsys: str, level: int, message: str) -> None:
        self.log.dout(subsys, level, message)

    def mark_started(self) -> None:
        """global_init complete: startup options freeze, async log starts."""
        self.conf.mark_started()
        self.log.start()

    async def shutdown(self) -> None:
        await self.asok.stop()
        self.log.stop()


def global_init(name: str, conf: Optional[Dict[str, Any]] = None,
                preload_plugins: bool = True) -> Context:
    """Daemon bring-up (reference src/global/global_init.cc): build the
    context, preload EC plugins per osd_erasure_code_plugins
    (global_init_preload_erasure_code, global_init.cc:586), freeze startup
    options."""
    ctx = Context(name, conf)
    if preload_plugins:
        from ceph_tpu.ec.registry import registry

        plugins = str(ctx.conf.get("osd_erasure_code_plugins", ""))
        directory = str(ctx.conf.get("erasure_code_dir", ""))
        registry.preload(",".join(plugins.replace(",", " ").split()), directory)
    ctx.mark_started()
    return ctx
