"""Throttles: bounded-resource backpressure primitives.

Role-equivalent of the reference's Throttle family (reference
src/common/Throttle.cc): a counted budget (bytes, ops) that producers
``get`` (blocking when exhausted, FIFO-fair) and consumers ``put`` back.
The messenger uses one per connection policy for dispatch bytes
(ms_dispatch_throttle_bytes); BlueStore-lite uses one for deferred bytes.
"""

from __future__ import annotations

import asyncio
import collections
from typing import Deque, Optional, Tuple


class Throttle:
    def __init__(self, name: str, max_amount: int):
        self.name = name
        self.max = max_amount
        self.current = 0
        self._waiters: Deque[Tuple[int, asyncio.Future]] = collections.deque()

    def past_midpoint(self) -> bool:
        return self.current >= self.max // 2

    def get_or_fail(self, amount: int) -> bool:
        """Non-blocking acquire (fast-dispatch path uses this).  Fails while
        blocking waiters are queued so it cannot starve them."""
        if self._waiters:
            return False
        if self.max and self.current + amount > self.max and self.current > 0:
            return False
        self.current += amount
        return True

    async def get(self, amount: int) -> None:
        """Blocking acquire, FIFO order so large requests can't starve.
        An idle throttle admits even an oversize request (ref behavior:
        a single op larger than the budget must not wedge)."""
        if self.max == 0 or (
            not self._waiters
            and (self.current + amount <= self.max or self.current == 0)
        ):
            self.current += amount
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append((amount, fut))
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # _wake already granted us the budget; hand it back
                self.current = max(0, self.current - amount)
            else:
                self._waiters = collections.deque(
                    (a, f) for a, f in self._waiters if f is not fut
                )
            self._wake()
            raise

    def would_admit(self, amount: int) -> bool:
        """True when get(amount) would return WITHOUT waiting — the
        messenger's rx batching peeks this before pulling another frame
        into a batch, because blocking on the throttle while holding
        undispatched frames (whose cost is only put() back after
        dispatch) would deadlock the serve loop against itself."""
        if self.max == 0:
            return True
        return not self._waiters and (
            self.current + amount <= self.max or self.current == 0)

    def put(self, amount: int) -> None:
        self.current = max(0, self.current - amount)
        self._wake()

    def _wake(self) -> None:
        while self._waiters:
            amount, fut = self._waiters[0]
            if self.current + amount > self.max and self.current > 0:
                return
            self._waiters.popleft()
            if not fut.done():
                self.current += amount
                fut.set_result(None)

    def reset_max(self, new_max: int) -> None:
        self.max = new_max
        self._wake()
