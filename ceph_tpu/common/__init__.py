"""Foundation/runtime layer (reference src/common/, src/log/, src/global/).

Everything above this layer — messenger, mon, OSD, EC plugins, tools —
consumes these services through a `CephContext`-equivalent bundle
(:class:`ceph_tpu.common.context.Context`): typed config with change
observers, perf counters, leveled per-subsystem logging with an in-memory
crash ring, an admin-socket command server, and throttles.
"""

from ceph_tpu.common.config import Config, Option, OPT_BOOL, OPT_FLOAT, OPT_INT, OPT_SECS, OPT_SIZE, OPT_STR
from ceph_tpu.common.context import Context
from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder, PerfCountersCollection
from ceph_tpu.common.throttle import Throttle

__all__ = [
    "Config",
    "Context",
    "Option",
    "OPT_BOOL",
    "OPT_FLOAT",
    "OPT_INT",
    "OPT_SECS",
    "OPT_SIZE",
    "OPT_STR",
    "PerfCounters",
    "PerfCountersBuilder",
    "PerfCountersCollection",
    "Throttle",
]
