"""Distributed-trace spans (zipkin/blkin + jaeger wrapper role).

Role-equivalent of the reference's ZTracer/jaeger integration (reference
src/common/zipkin_trace.h, src/common/tracer.{h,cc}): ops carry a trace
with named spans; pipeline stages open child spans ("start ec write",
per-shard sub-writes, ECBackend.cc:2027,2113) and annotate events.

Cross-daemon stitching: ids are RANDOM 64-bit hex strings (unique across
processes and hosts, not a per-process counter), and a (trace_id,
parent span_id) pair rides the wire on the data-plane messages
(MOSDOp, MECSubWrite/Reply, MOSDBackoff, MOSDPGHitSet — types.py).  The
receiving daemon calls ``Tracer.join`` to open a child span of the
remote parent, so a client write stitches into ONE tree:
client_op -> osd_op -> ec write -> k+m ec_sub_write spans, each span
recorded in its OWN daemon's ring.

Spans land in a bounded per-daemon ring dumped via the admin socket
(``dump_traces``; ``dump_trace`` filters one trace_id) — the in-process
stand-in for shipping to a collector.  ``tools/trace_export.py`` gathers
the per-daemon rings and emits Jaeger-compatible JSON for a whole op.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Deque, Dict, List, Optional

import time


def _new_id() -> str:
    """Random 64-bit hex id: unique across daemons/hosts (a per-process
    counter would collide the moment two daemons' spans stitch)."""
    return os.urandom(8).hex()


class Span:
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "end", "events", "tags")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.events: List[Dict[str, Any]] = []
        self.tags: Dict[str, Any] = {}

    def event(self, name: str) -> None:
        self.events.append({"time": time.time(), "event": name})

    def tag(self, key: str, value: Any) -> "Span":
        """Attach a key/value annotation (zipkin binary-annotation role):
        the batching queue tags dispatch spans with lane, group size, and
        byte counts so the asok timeline is self-describing."""
        self.tags[key] = value
        return self

    def child(self, name: str) -> "Span":
        return self.tracer._span(name, self.trace_id, self.span_id)

    def context(self):
        """(trace_id, span_id) — what rides the wire so the receiving
        daemon can ``join`` as a child of this span."""
        return self.trace_id, self.span_id

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()
            self.tracer._record(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def dump(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start": self.start,
                "duration": (self.end or time.time()) - self.start,
                "events": list(self.events), "tags": dict(self.tags)}


class Tracer:
    def __init__(self, max_spans: int = 256, enabled: bool = True,
                 service: str = ""):
        self.enabled = enabled
        # the daemon name, stamped into every dumped span so a
        # cross-daemon trace export can label processes (jaeger's
        # processes map) without knowing which ring a span came from
        self.service = service
        self._ring: Deque[Span] = collections.deque(maxlen=max_spans)
        self._lock = threading.Lock()

    def new_trace(self, name: str) -> Span:
        return self._span(name, _new_id(), None)

    def join(self, name: str, trace_id: str,
             parent_id: Optional[str] = None) -> Span:
        """Open a span under a REMOTE parent: the receiving half of
        cross-daemon propagation (the wire carried (trace_id,
        parent span_id); this daemon's span becomes its child)."""
        return self._span(name, trace_id, parent_id or None)

    def _span(self, name: str, trace_id: str, parent_id: Optional[str]) -> Span:
        return Span(self, name, trace_id, parent_id)

    def _record(self, span: Span) -> None:
        if self.enabled:
            with self._lock:
                self._ring.append(span)

    def dump(self) -> List[Dict[str, Any]]:
        # snapshot FIRST: worker threads finish spans concurrently, and
        # iterating the live deque from the asok thread would raise
        # "deque mutated during iteration" mid-dump
        with self._lock:
            spans = list(self._ring)
        out = []
        for s in spans:
            d = s.dump()
            if self.service:
                d["service"] = self.service
            out.append(d)
        return out

    def spans_for(self, trace_id: str) -> List[Dict[str, Any]]:
        """Every recorded span of one trace (the `dump_trace <id>` asok
        answer; tools/trace_export.py stitches these across daemons)."""
        return [d for d in self.dump() if d["trace_id"] == trace_id]

    def register_asok(self, asok) -> None:
        asok.register("dump_traces", lambda a: self.dump(),
                      "recent trace spans")
        asok.register(
            "dump_trace",
            lambda a: {"trace_id": a.get("trace_id", ""),
                       "spans": self.spans_for(a.get("trace_id", ""))},
            "spans of one trace (trace_id=<hex>)")
