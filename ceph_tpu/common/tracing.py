"""Distributed-trace spans (zipkin/blkin + jaeger wrapper role).

Role-equivalent of the reference's ZTracer/jaeger integration (reference
src/common/zipkin_trace.h, src/common/tracer.{h,cc}): ops carry a trace
with named spans; pipeline stages open child spans ("start ec write",
per-shard sub-writes, ECBackend.cc:2027,2113) and annotate events.  Spans
land in a bounded per-daemon ring dumped via the admin socket
(`dump_traces`) — the in-process stand-in for shipping to a collector.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Any, Deque, Dict, List, Optional

_ids = itertools.count(1)


class Span:
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "end", "events", "tags")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 parent_id: Optional[int]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.events: List[Dict[str, Any]] = []
        self.tags: Dict[str, Any] = {}

    def event(self, name: str) -> None:
        self.events.append({"time": time.time(), "event": name})

    def tag(self, key: str, value: Any) -> "Span":
        """Attach a key/value annotation (zipkin binary-annotation role):
        the batching queue tags dispatch spans with lane, group size, and
        byte counts so the asok timeline is self-describing."""
        self.tags[key] = value
        return self

    def child(self, name: str) -> "Span":
        return self.tracer._span(name, self.trace_id, self.span_id)

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()
            self.tracer._record(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def dump(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start": self.start,
                "duration": (self.end or time.time()) - self.start,
                "events": list(self.events), "tags": dict(self.tags)}


class Tracer:
    def __init__(self, max_spans: int = 256, enabled: bool = True):
        self.enabled = enabled
        self._ring: Deque[Span] = collections.deque(maxlen=max_spans)

    def new_trace(self, name: str) -> Span:
        return self._span(name, next(_ids), None)

    def _span(self, name: str, trace_id: int, parent_id: Optional[int]) -> Span:
        return Span(self, name, trace_id, parent_id)

    def _record(self, span: Span) -> None:
        if self.enabled:
            self._ring.append(span)

    def dump(self) -> List[Dict[str, Any]]:
        # snapshot FIRST (one C-level call, safe under the GIL): the
        # batching worker thread finishes dispatch spans concurrently,
        # and iterating the live deque from the asok thread would raise
        # "deque mutated during iteration" mid-dump
        return [s.dump() for s in list(self._ring)]

    def register_asok(self, asok) -> None:
        asok.register("dump_traces", lambda a: self.dump(),
                      "recent trace spans")
