"""Leveled, per-subsystem logging with an in-memory crash ring.

Role-equivalent of the reference's dout/dendl + src/log/Log.cc: each log
call carries a subsystem and level; the gather level (``debug_<subsys>``
config options) decides whether it is emitted to the sink, but recent
entries are ALWAYS kept in a bounded in-memory ring so a crash dump
(``dump_recent``) shows high-verbosity history even when the on-disk level
was low — the reference's signature debugging affordance.  Writes to the
sink happen on a background thread (async Log thread) so the hot path only
appends to a deque.

Per-subsystem levels are CACHED (the reference's SubsystemMap): the hot
path pays one dict lookup, not a layered-config resolution per ``dout``
call.  ``invalidate_levels()`` drops the cache; the Context wires it to a
``debug_*`` config observer so runtime ``config set debug_ms 10`` (asok or
``ceph tell``) takes effect immediately.  ``wants(subsys, level)`` is the
call-site guard hot paths use so a disabled high-verbosity dout costs a
cached compare, not a ring append.

Error entries are additionally PINNED in a separate bounded ring (the
reference's m_recent vs gather split): ``dump_recent`` shows them even
when the main ring wrapped between the error and the crash.
"""

from __future__ import annotations

import collections
import queue
import sys
import threading
import time
import traceback
from typing import Deque, Dict, List, Optional, TextIO, Tuple

Entry = Tuple[float, str, int, str]  # (stamp, subsys, level, message)


class Log:
    def __init__(self, conf=None, sink: Optional[TextIO] = None, name: str = ""):
        self.conf = conf
        self.name = name
        self.sink = sink if sink is not None else sys.stderr
        max_recent = 500
        if conf is not None:
            try:
                max_recent = int(conf.get("log_max_recent", 500))
            except Exception:
                pass
        self._recent: Deque[Entry] = collections.deque(maxlen=max_recent)
        # errors pinned separately: a wrapped ring cannot lose them
        self._recent_errors: Deque[Entry] = collections.deque(
            maxlen=max(32, max_recent // 8))
        self._queue: "queue.Queue[Optional[Entry]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # subsys -> gather level; rebuilt lazily after invalidate_levels()
        self._levels: Dict[str, int] = {}

    # -- levels --------------------------------------------------------------

    def gather_level(self, subsys: str) -> int:
        """Cached per-subsystem emit level (debug_<subsys>); one dict
        lookup on the hot path instead of a config resolution."""
        level = self._levels.get(subsys)
        if level is None:
            level = self._resolve_level(subsys)
            self._levels[subsys] = level
        return level

    def _resolve_level(self, subsys: str) -> int:
        if self.conf is None:
            return 1
        try:
            return int(self.conf.get(f"debug_{subsys}", 1))
        except Exception:
            return 1

    def invalidate_levels(self) -> None:
        """Drop the level cache (a debug_* option changed at runtime)."""
        self._levels = {}

    def wants(self, subsys: str, level: int) -> bool:
        """Call-site guard for hot-path douts: would this entry emit?
        Guarded douts skip the ring too — turning the level up is what
        starts capturing them (the runtime-diagnostic workflow)."""
        return level <= self.gather_level(subsys)

    # -- hot path ------------------------------------------------------------

    def dout(self, subsys: str, level: int, message: str) -> None:
        entry = (time.time(), subsys, level, message)
        with self._lock:
            self._recent.append(entry)
            if level < 0:
                self._recent_errors.append(entry)
        if level <= self.gather_level(subsys):
            self._emit(entry)

    def error(self, subsys: str, message: str) -> None:
        self.dout(subsys, -1, message)

    def _emit(self, entry: Entry) -> None:
        if self._thread is not None:
            self._queue.put(entry)
        else:
            self._write(entry)

    def _write(self, entry: Entry) -> None:
        stamp, subsys, level, message = entry
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(stamp))
        frac = f"{stamp % 1:.6f}"[1:]
        try:
            self.sink.write(f"{ts}{frac} {self.name} {level:2d} {subsys}: {message}\n")
        except ValueError:
            pass  # sink closed at interpreter teardown

    # -- async writer --------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"log-{self.name}")
            self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=2)
            self._thread = None

    def flush(self) -> None:
        """Drain pending async writes (asok `log flush` equivalent)."""
        if self._thread is not None:
            self._queue.join()

    def _run(self) -> None:
        while True:
            entry = self._queue.get()
            try:
                if entry is None:
                    return
                self._write(entry)
            finally:
                self._queue.task_done()

    # -- crash ring ----------------------------------------------------------

    def dump_recent(self, out: Optional[TextIO] = None) -> List[Entry]:
        """Dump the full ring at max verbosity (crash handler path),
        merged with the pinned error entries the ring may have wrapped
        past (same-object dedupe, stamp order)."""
        with self._lock:
            entries = list(self._recent)
            pinned = list(self._recent_errors)
        ring_ids = {id(e) for e in entries}
        extra = [e for e in pinned if id(e) not in ring_ids]
        if extra:
            entries = sorted(entries + extra, key=lambda e: e[0])
        if out is not None:
            out.write(f"--- begin dump of recent events ({self.name}) ---\n")
            for e in entries:
                stamp, subsys, level, message = e
                out.write(f"{stamp:.6f} {level:3d} {subsys}: {message}\n")
            out.write("--- end dump of recent events ---\n")
        return entries

    def dump_on_exception(self, exc: BaseException) -> List[Entry]:
        self.sink.write("".join(traceback.format_exception(exc)))
        return self.dump_recent(self.sink)
