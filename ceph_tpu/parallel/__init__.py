"""Device mesh, shardings, and the batched EC dispatch service."""
