"""Stripe-batching dispatch queue — amortizing many small EC ops into one
device call.

The reference dispatches its codec once per 4 KiB-unit stripe inside
ECUtil::encode (reference src/osd/ECUtil.cc:123-160) and per 1 MiB buffer in
the benchmark; a TPU dispatch has fixed launch latency, so the >=10x target
"lives or dies on the batching queue" (SURVEY.md §7 hard part 2).  This
queue aggregates encode/decode requests from many objects/ops, concatenates
them column-wise into one [rows, sum(B)] buffer per (matrix, layout) group,
runs ONE bit-plane matmul, and fans completions back out — the same
submit -> aggregate -> dispatch -> completion-fan-out pipeline ECBackend's
write path drives (submit_transaction -> ... -> try_reads_to_commit,
ECBackend.cc:1525->1989).

Threading model: submit() is non-blocking and returns a Future; a worker
thread flushes when pending bytes cross `max_pending_bytes` or `max_delay`
elapses, whichever first.  flush() forces a synchronous drain (used by
tests and by the benchmark's timed sections).

BIT-PLANAR RESIDENCY (the measured ~1.6x win, ceph_tpu/ops/gf2.py
writeup): `submit_planar` dispatches over shards that already live in HBM
as int8 bit-planes — matmul only, no unpack/pack — and resolves to planar
device buffers, so encode -> decode -> recovery chain on-device.
`PlanarShardStore` is the residency manager: an LRU-bounded HBM cache of
planar shard rows where bytes pay the pack/unpack boundary exactly once,
when they enter or leave the device tier (the reference's analog is the
buffer staying in L2/registers across ECUtil::encode's per-stripe loop,
reference src/osd/ECUtil.cc:123-160; on a TPU the "stay resident" scope
is HBM across whole pipeline stages).

PACKED-BIT PRODUCTION LANE (the measured 1.45x over int8 planes,
ceph_tpu/ops/gf2.py lane-promotion writeup): for w=8 byte-layout codes
the resident trio has a u32-word mirror — `submit_packedbit` (bytes in,
bytes out), `submit_packedbit_resident` (bytes in, parity bytes + u32
planes out), `submit_packedbit_planes` (resident planes in/out) — each
dispatch running the matrix as a static XOR schedule compiled per matrix
(encode generators and decode signatures alike) behind the gf2 LRU.
Residents store at 1 HBM byte per data byte instead of 8, so the same
store budget holds 8x the objects.

DEVICE-DISPATCH CIRCUIT BREAKER (the robustness layer): every lane owns a
breaker with three states.  CLOSED: dispatches go to the device; one that
RAISES is rescued host-side (the group's futures resolve with
byte-identical numpy GF(2) results — submitters never see the device
die) and trips the lane OPEN; one that completes but exceeds
``dispatch_timeout`` trips it after the fact.  OPEN: the lane's groups
are served by the CPU mirrors (``_cpu_apply_request``) until the
cooldown elapses (doubling per consecutive trip, capped).  HALF-OPEN:
one group re-probes the device; success closes the breaker, failure
re-opens it.  ``inject_dispatch_delay`` (osd_debug_inject_dispatch_delay
/ CEPH_TPU_INJECT_DISPATCH_DELAY) slows dispatches to exercise the
watchdog.  Counted in `ec_tpu`: breaker_trip / breaker_probe /
breaker_recover / breaker_fallback + the breaker_open_lanes gauge.

OBSERVABILITY (the `ec_tpu` + `planar_store` counter sets): the queue owns
a PerfCounters set — name -> meaning -> kind in _build_ec_tpu_perf — with
per-lane submit/byte counters (submit_<lane>/bytes_<lane>, u64), queue-wait
and device-dispatch longrunavg latencies (queue_wait, dispatch_dev), a
coalesced-group-size histogram (group_size), and flush-cause counters
(flush_bytes/flush_delay/flush_forced, u64).  Daemons add the set to their
PerfCountersCollection (`perf dump`, mgr prometheus); `dump_timeline()`
backs the `dump_ec_batch_timeline` asok command with the last 128
dispatches (lane, group size, bytes, wait, device seconds).  Trace spans
ride submissions: a `span=` parent (the OSD's `ec write` trace) gets
submit/coalesce/fan-out events plus a per-dispatch child span tagged with
lane/group_size/bytes.  PlanarShardStore mirrors its residency stats into
a `planar_store` set: admit/hit/miss/evict (u64), resident_bytes + entries
(gauges), and pack_s/unpack_s longrunavg — the host<->device boundary
seconds paid at admit()/read().
"""

from __future__ import annotations

import collections
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder

#: the six dispatch lanes, in promotion order (int8 trio, packed-bit trio)
LANES = ("packed", "planar", "resident",
         "packedbit", "packedbit_resident", "packedbit_planes")


def _build_ec_tpu_perf() -> PerfCounters:
    """The `ec_tpu` counter set (COUNTER SCHEMA below; dumped via `perf
    dump` on any daemon sharing the process queue, exported by the mgr
    prometheus module, snapshotted into BENCH records):

      submit               u64         requests accepted, all lanes
      submit_<lane>        u64         requests accepted per lane
      bytes_<lane>         u64         packed-equivalent bytes submitted per lane
      dispatch             u64         device calls issued
      sharded_dispatch     u64         dispatches laid across the mesh
      overlapped_rounds    u64         rounds whose launch overlapped a fetch
      bytes                u64         bytes dispatched (incl. bucket padding)
      queue_wait           longrunavg  submit -> launch wait per request
      dispatch_dev         longrunavg  launch -> fan-out device seconds per dispatch
      group_size           histogram   coalesced requests per dispatch (pow2 buckets)
      submit_group         u64         multi-item submit_group() calls (the
                                       whole-stripe-group handoff seam)
      group_submit_size    histogram   items per submit_group() call
      flush_bytes          u64         rounds cut by the bytes threshold
      flush_delay          u64         rounds cut by max_delay expiry
      flush_forced         u64         rounds cut by an explicit flush()/close()
    """
    b = PerfCountersBuilder("ec_tpu")
    b.add_u64_counter("submit", "requests accepted across all lanes")
    b.add_u64_counter("dispatch", "device calls issued")
    b.add_u64_counter("sharded_dispatch",
                      "dispatches that ran across the device mesh")
    b.add_u64_counter("overlapped_rounds",
                      "rounds whose launch overlapped the previous fetch")
    b.add_u64_counter("bytes",
                      "bytes dispatched to the device (incl. padding)")
    for lane in LANES:
        b.add_u64_counter(f"submit_{lane}", f"requests on the {lane} lane")
        b.add_u64_counter(f"bytes_{lane}",
                          f"packed-equivalent bytes submitted on {lane}")
    b.add_time_avg("queue_wait", "submit -> launch coalescing wait")
    b.add_time_avg("dispatch_dev", "launch -> fan-out device time")
    b.add_histogram("group_size", "coalesced requests per dispatch")
    b.add_u64_counter("submit_group", "multi-item group submits")
    b.add_histogram("group_submit_size", "items per group submit")
    b.add_u64_counter("flush_bytes", "rounds flushed by the bytes threshold")
    b.add_u64_counter("flush_delay", "rounds flushed by max_delay expiry")
    b.add_u64_counter("flush_forced", "rounds flushed by explicit flush()")
    b.add_u64_counter("breaker_trip",
                      "lane breaker trips (dispatch raised or exceeded "
                      "dispatch_timeout)")
    b.add_u64_counter("breaker_probe", "half-open device re-probes")
    b.add_u64_counter("breaker_recover",
                      "breakers closed by a successful probe")
    b.add_u64_counter("breaker_fallback",
                      "groups served by the host CPU path (breaker open "
                      "or dispatch failure rescue)")
    b.add_u64("breaker_open_lanes", "lanes currently tripped open (gauge)")
    return b.create_perf_counters()


# -- host-side GF(2) mirrors (the circuit-breaker CPU fallback path) ---------
# Byte-for-byte numpy mirrors of the device lanes in ceph_tpu/ops/gf2.py:
# GF(2) arithmetic is exact, so a group served here fans out results
# BYTE-IDENTICAL to what the device lane would have produced (the content
# gates in tests/test_batching.py hold across the failover).  Kept
# jax-free on purpose — this path must work when the device stack is the
# thing that is broken.


def _np_unpack_bits(data: np.ndarray, w: int) -> np.ndarray:
    """[n, B] uint8 chunks -> [n*w, Bc] int8 bit-planes (mirror of
    ops/gf2.unpack_bits_bytes for w in 4/8/16)."""
    n, B = data.shape
    if w == 16:
        pairs = data.reshape(n, B // 2, 2)
        planes = [((pairs[:, :, x // 8] >> (x % 8)) & 1) for x in range(16)]
        return np.stack(planes, axis=1).reshape(n * 16, B // 2).astype(np.int8)
    if w == 4:
        shifts = np.arange(4, dtype=np.uint8)
        lo = (data[:, None, :] >> shifts[None, :, None]) & 1
        hi = (data[:, None, :] >> (shifts + 4)[None, :, None]) & 1
        return np.stack([lo, hi], axis=-1).reshape(n * 4, B * 2).astype(np.int8)
    shifts = np.arange(8, dtype=np.uint8)
    return (((data[:, None, :] >> shifts[None, :, None]) & 1)
            .reshape(n * 8, B).astype(np.int8))


def _np_pack_bits(bits: np.ndarray, w: int, out_rows: int) -> np.ndarray:
    """Inverse of _np_unpack_bits (mirror of ops/gf2.pack_bits_bytes)."""
    if w == 16:
        Bc = bits.shape[1]
        planes = bits.reshape(out_rows, 16, Bc).astype(np.int32)
        lo = np.zeros((out_rows, Bc), np.int32)
        hi = np.zeros((out_rows, Bc), np.int32)
        for x in range(8):
            lo = lo + (planes[:, x] << x)
            hi = hi + (planes[:, x + 8] << x)
        return np.stack([lo, hi], axis=-1).reshape(out_rows, Bc * 2) \
            .astype(np.uint8)
    if w == 4:
        Bc2 = bits.shape[1]
        planes = bits.reshape(out_rows, 4, Bc2 // 2, 2).astype(np.int32)
        shifts = np.arange(4, dtype=np.int32)
        lo = np.sum(planes[..., 0] << shifts[None, :, None], axis=1)
        hi = np.sum(planes[..., 1] << shifts[None, :, None], axis=1)
        return (lo | (hi << 4)).astype(np.uint8)
    Bc = bits.shape[1]
    planes = bits.reshape(out_rows, 8, Bc).astype(np.int32)
    shifts = np.arange(8, dtype=np.int32)
    return np.sum(planes << shifts[None, :, None], axis=1).astype(np.uint8)


def _np_matmul_gf2(mbits: np.ndarray, bits: np.ndarray) -> np.ndarray:
    return ((np.asarray(mbits, dtype=np.int32)
             @ np.asarray(bits, dtype=np.int32)) & 1).astype(np.int8)


def _np_words(bits: np.ndarray) -> np.ndarray:
    """[R, B] 0/1 bit rows -> [R, B//32] uint32 plane words (mirror of
    ops/gf2._bits_to_words / pack_bitplanes_u32's word layout)."""
    return np.packbits(bits.astype(np.uint8), axis=1,
                       bitorder="little").view(np.uint32)


def _cpu_apply_request(kind: str, mbits: np.ndarray, regions, w: int,
                       out_rows: int):
    """Serve ONE lane request host-side; returns exactly what the device
    lane's fan-out would have resolved the request's future with (device
    buffers become numpy arrays — every consumer accepts both)."""
    mb = np.asarray(mbits, dtype=np.uint8)
    if kind in ("packed", "packedbit"):
        bits = _np_unpack_bits(np.asarray(regions, dtype=np.uint8), w)
        return _np_pack_bits(_np_matmul_gf2(mb, bits), w, out_rows)
    if kind == "planar":
        return _np_matmul_gf2(mb, np.asarray(regions))
    if kind == "resident":
        bits = _np_unpack_bits(np.asarray(regions, dtype=np.uint8), w)
        pbits = _np_matmul_gf2(mb, bits)
        return (_np_pack_bits(pbits, w, out_rows),
                np.concatenate([bits, pbits], axis=0))
    if kind == "packedbit_resident":
        bits = _np_unpack_bits(np.asarray(regions, dtype=np.uint8), 8)
        pbits = _np_matmul_gf2(mb, bits)
        return (_np_pack_bits(pbits, 8, out_rows),
                np.concatenate([_np_words(bits), _np_words(pbits)], axis=0))
    if kind == "packedbit_planes":
        pl = np.asarray(regions)
        out = np.zeros((mb.shape[0], pl.shape[1]), dtype=pl.dtype)
        for r in range(mb.shape[0]):
            cols = np.nonzero(mb[r])[0]
            if len(cols):
                out[r] = np.bitwise_xor.reduce(pl[cols], axis=0)
        return out
    raise ValueError(f"unknown lane kind {kind!r}")


class _LaneBreaker:
    """Per-lane circuit breaker state.  closed -> (trip) -> open ->
    (cooldown elapses) -> one half-open probe -> closed on success, or
    re-open with doubled cooldown on failure."""

    __slots__ = ("state", "open_until", "cooldown", "probing")

    CLOSED = "closed"
    OPEN = "open"

    def __init__(self):
        self.state = self.CLOSED
        self.open_until = 0.0
        self.cooldown = 0.0
        self.probing = False


class _Request(NamedTuple):
    """One queued lane submission.  t_submit feeds the queue_wait
    latency; span threads the submitter's trace (the OSD's `ec write`)
    through coalesce -> dispatch -> fan-out."""

    regions: Any
    future: Future
    t_submit: float
    span: Any = None


@dataclass
class _Group:
    mbits: np.ndarray
    w: int
    out_rows: int
    # dispatch lane: "packed" (unpack+matmul+pack fused per dispatch),
    # "planar" (matmul-only over resident int8 bit-planes), "resident"
    # (packed in -> packed parity + planar rows out, the write path);
    # plus the packed-bit production trio mirroring them over u32 plane
    # words + static XOR schedules (ceph_tpu/ops/gf2.py lane promotion):
    # "packedbit", "packedbit_planes", "packedbit_resident"
    kind: str = "packed"
    requests: List[_Request] = field(default_factory=list)
    pending_bytes: int = 0


@dataclass
class _Launched:
    """One launched dispatch awaiting completion (fan-out)."""

    group: _Group
    state: Any
    t_launch: float
    span: Any = None  # child of a submitter's trace, or queue-tracer root
    wait_s: float = 0.0  # mean submit->launch wait across the group


class BatchingQueue:
    def __init__(
        self,
        # 16 MiB/dispatch: the measured HBM sweet spot for the planar
        # pipeline (bench.py r4 sweep — the 8x bit-plane expansion makes
        # 64 MiB batches HBM-bound on v5e; 2 MiB of columns at k=8 wins)
        max_pending_bytes: int = 16 << 20,
        max_delay: Optional[float] = None,
        use_pallas: Optional[bool] = None,
        mesh=None,
    ):
        import os as _os

        self.max_pending_bytes = max_pending_bytes
        # the DEFAULT coalescing window is tunable (CEPH_TPU_BATCH_DELAY
        # seconds): loaded CI hosts widen it so coalescing tests assert
        # the MECHANISM rather than the 2ms default's luck.  An explicit
        # max_delay argument always wins, and a malformed value falls
        # back rather than crashing the first EC write.
        if max_delay is None:
            try:
                max_delay = float(
                    _os.environ.get("CEPH_TPU_BATCH_DELAY") or 0.002)
            except ValueError:
                max_delay = 0.002
        self.max_delay = max_delay
        self._use_pallas = use_pallas
        # device-mesh execution (ceph_tpu/parallel/mesh.py): when a mesh
        # is attached (or auto-engages on a multi-chip backend), every
        # dispatch lane lays its batch out across the mesh's column axis
        # — the same compiled ops run SPMD over all devices, collectives
        # inserted by XLA where a consumer needs them.  mesh=None means
        # auto-detect; mesh=False pins the queue single-device (bench
        # arms and n=1 dryruns that must not auto-engage).
        if mesh is None:
            from ceph_tpu.parallel.mesh import shared_mesh

            mesh = shared_mesh()
        self.mesh = mesh or None
        # the ec_tpu perf counter set (schema: _build_ec_tpu_perf).  The
        # legacy bare ints (submits/dispatches/bytes_dispatched/...) are
        # now read-only views over it — daemons add this set to their
        # PerfCountersCollection so `perf dump` carries the full breakdown.
        self.perf = _build_ec_tpu_perf()
        # optional per-daemon Tracer: dispatch spans with no submitter
        # parent (e.g. bench traffic) root here; the OSD attaches its ctx
        # tracer so spans land in its dump_traces ring
        self.tracer = None
        # bounded ring of recent dispatches for `dump_ec_batch_timeline`
        self.timeline: "collections.deque" = collections.deque(maxlen=128)
        # -- device-dispatch watchdog + per-lane circuit breaker ------------
        # A dispatch that RAISES is rescued host-side immediately (its
        # requests resolve with byte-identical numpy results) and trips
        # the lane's breaker; one that completes but exceeds
        # dispatch_timeout trips it after the fact (the results were
        # fine, the lane is slow/sick).  While a breaker is OPEN the
        # lane's groups are served by the CPU mirrors; after
        # breaker_cooldown (doubling per consecutive trip, capped at
        # breaker_cooldown_max) ONE group re-probes the device —
        # success closes the breaker (half-open re-engage).
        try:
            self.dispatch_timeout = float(
                _os.environ.get("CEPH_TPU_DISPATCH_TIMEOUT") or 30.0)
        except ValueError:
            self.dispatch_timeout = 30.0
        # osd_debug_inject_dispatch_delay: slow every device dispatch by
        # this many seconds (exercises the watchdog/breaker; 0 = off)
        try:
            self.inject_dispatch_delay = float(
                _os.environ.get("CEPH_TPU_INJECT_DISPATCH_DELAY") or 0.0)
        except ValueError:
            self.inject_dispatch_delay = 0.0
        self.breaker_cooldown = 1.0
        self.breaker_cooldown_max = 30.0
        self._breakers: Dict[str, _LaneBreaker] = {}
        self._breaker_lock = threading.Lock()
        # test seam: invoked (worker thread) after a round is launched,
        # before the backlog check — lets tests inject a standing backlog
        # deterministically instead of racing thread schedulers
        self._launch_hook = None
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._groups: Dict[Tuple, _Group] = {}
        self._pending = 0
        self._oldest: Optional[float] = None
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True, name="ec-batch")
        self._worker.start()

    # -- legacy counter views (the pre-instrumentation bare ints) ------------

    @property
    def submits(self) -> int:
        return self.perf.get("submit")

    @property
    def dispatches(self) -> int:
        return self.perf.get("dispatch")

    @property
    def bytes_dispatched(self) -> int:
        return self.perf.get("bytes")

    @property
    def sharded_dispatches(self) -> int:
        return self.perf.get("sharded_dispatch")

    @property
    def overlapped_rounds(self) -> int:
        return self.perf.get("overlapped_rounds")

    def dump_timeline(self, count: int = 32) -> List[Dict[str, Any]]:
        """Most-recent-first dispatch records for the asok command
        `dump_ec_batch_timeline`: lane, group size, bytes, queue wait,
        device time, and whether the dispatch ran sharded."""
        return list(self.timeline)[-max(1, int(count)):][::-1]

    def register_asok(self, asok) -> None:
        """Expose the dispatch timeline on a daemon's admin socket
        (`dump_ec_batch_timeline [count=N]`)."""
        asok.register(
            "dump_ec_batch_timeline",
            lambda a: self.dump_timeline(int(a.get("count", 32))),
            "recent EC batch dispatches (lane, group size, wait, device s)")

    # -- client side ---------------------------------------------------------

    def submit(
        self, mbits: np.ndarray, regions: np.ndarray, w: int, out_rows: int,
        span=None,
    ) -> "Future[np.ndarray]":
        """Queue (mbits @ regions) over the byte layout; resolves to the
        [out_rows, B] parity/reconstruction buffer."""
        return self._submit(mbits, regions, w, out_rows, "packed", span)

    def submit_planar(
        self, mbits: np.ndarray, bits, w: int, out_rows: int, span=None
    ) -> "Future[object]":
        """Queue (mbits @ bits) over ALREADY-PLANAR device bit-planes
        ([rows*w, Bcols] int8); resolves to the [out_rows*w, Bcols] planar
        device buffer — no pack, the result stays HBM-resident for the
        next pipeline stage."""
        return self._submit(mbits, bits, w, out_rows, "planar", span)

    def submit_resident(
        self, mbits: np.ndarray, rows: np.ndarray, w: int, out_rows: int,
        span=None,
    ) -> "Future[object]":
        """The residency WRITE path: packed [n, B] uint8 rows in, ONE
        fused batched device call (unpack + matmul + parity pack), and
        the future resolves to (packed_parity np [out_rows, B],
        all_bits planar [(n+out_rows)*w, Bc]) — parity bytes for
        persistence, planar rows to keep HBM-resident.  Submission is
        non-blocking (no device work on the caller's thread), so
        concurrent ops coalesce exactly like the packed lane."""
        return self._submit(mbits, rows, w, out_rows, "resident", span)

    # -- packed-bit lanes (the production w=8 trio, ceph_tpu/ops/gf2.py
    #    lane-promotion writeup: u32-word bit-planes + static XOR
    #    schedules compiled per matrix behind the LRU) ----------------------

    def submit_packedbit(
        self, mbits: np.ndarray, regions: np.ndarray, w: int, out_rows: int,
        span=None,
    ) -> "Future[np.ndarray]":
        """Queue a [out_rows*8, n*8] GF(2) bit-matrix over packed [n, B]
        uint8 rows through the packed-bit XOR-schedule lane (one fused
        unpack -> u32 words -> schedule -> byte pack device call per
        coalesced group); resolves to the [out_rows, B] parity or
        reconstruction buffer.  Encode generators AND per-decode-
        signature matrices both land here — each matrix is its own
        dispatch group and its own LRU-cached compiled schedule."""
        assert w == 8, "packed-bit lane is the w=8 byte-layout lane"
        return self._submit(mbits, regions, w, out_rows, "packedbit", span)

    def submit_packedbit_resident(
        self, mbits: np.ndarray, rows: np.ndarray, w: int, out_rows: int,
        span=None,
    ) -> "Future[object]":
        """Packed-bit residency WRITE path: packed [n, B] uint8 rows in
        (B % 32 == 0), resolves to (packed_parity np [out_rows, B],
        all_planes u32 [(n+out_rows)*8, B//32]) — parity bytes for
        persistence, u32 plane words to stay HBM-resident at 1/8th the
        int8-plane footprint."""
        assert w == 8, "packed-bit lane is the w=8 byte-layout lane"
        if rows.shape[1] % 32:
            # reject at SUBMISSION: a misaligned request that reached
            # launch would fail every innocent request coalesced with it
            raise ValueError(
                "packedbit_resident requests must be 32-byte-column "
                f"aligned, got width {rows.shape[1]}")
        return self._submit(mbits, rows, w, out_rows, "packedbit_resident",
                            span)

    def submit_packedbit_planes(
        self, mbits: np.ndarray, planes, w: int, out_rows: int, span=None
    ) -> "Future[object]":
        """Queue an XOR schedule over ALREADY-RESIDENT u32 plane words
        ([rows*8, Wc] uint32); resolves to the [out_rows*8, Wc] device
        buffer — no pack, the result stays resident for the next stage
        (the packed-bit mirror of submit_planar)."""
        assert w == 8, "packed-bit lane is the w=8 byte-layout lane"
        return self._submit(mbits, planes, w, out_rows, "packedbit_planes",
                            span)

    def submit_group(self, items, span=None) -> List[Future]:
        """Group-aware submit (the messenger/recovery whole-stripe-group
        handoff seam): queue a LIST of lane submissions — each item is
        (mbits, regions, w, out_rows, kind) — under ONE lock acquisition
        and ONE worker wakeup, so a coalesced group of objects reaches
        the EC tier as a single buffer-list submission instead of N
        contended submits.  Items sharing a dispatch signature land in
        the same _Group exactly as per-item submits would; returns the
        per-item futures, index-aligned."""
        futs: List[Future] = []
        sizes: List[int] = []
        now = time.monotonic()
        if span is not None:
            span.event(f"ec submit group n={len(items)}")
        with self._cv:
            if self._stop:
                raise RuntimeError("BatchingQueue is closed")
            for mbits, regions, w, out_rows, kind in items:
                fut: Future = Future()
                futs.append(fut)
                sizes.append(self._queue_locked(
                    mbits, regions, w, out_rows, kind, fut, now, span))
            if items:
                self._cv.notify()
        for (_, _, _, _, kind), nbytes in zip(items, sizes):
            self.perf.inc("submit")
            self.perf.inc(f"submit_{kind}")
            self.perf.inc(f"bytes_{kind}", nbytes)
        if len(items) > 1:
            self.perf.inc("submit_group")
            self.perf.hinc("group_submit_size", len(items))
        return futs

    def _queue_locked(self, mbits, regions, w, out_rows, kind, fut,
                      now, span) -> int:
        """Insert one request into its dispatch group (caller holds the
        lock).  Returns the packed-equivalent byte size counted."""
        # the full dispatch signature: identical matrix BYTES under a
        # different w or output arity is a different computation; the
        # three lanes never share a dispatch (different layouts)
        key = (w, out_rows, kind, mbits.shape, mbits.tobytes())
        group = self._groups.get(key)
        if group is None:
            group = self._groups[key] = _Group(
                mbits=mbits, w=w, out_rows=out_rows, kind=kind)
        group.requests.append(_Request(regions, fut, now, span))
        # planar bit-plane submissions are 8x-expanded int8: count
        # their packed-equivalent size or the lane would flush at 1/8
        # the measured batch sweet spot
        nbytes = self._req_bytes(kind, mbits, regions)
        group.pending_bytes += nbytes
        self._pending += nbytes
        if self._oldest is None:
            self._oldest = now
        return nbytes

    def _submit(self, mbits, regions, w, out_rows, kind,
                span=None) -> Future:
        fut: Future = Future()
        now = time.monotonic()
        if span is not None:
            span.event(f"ec submit lane={kind}")
        with self._cv:
            if self._stop:
                raise RuntimeError("BatchingQueue is closed")
            nbytes = self._queue_locked(mbits, regions, w, out_rows, kind,
                                        fut, now, span)
            self._cv.notify()
        self.perf.inc("submit")
        self.perf.inc(f"submit_{kind}")
        self.perf.inc(f"bytes_{kind}", nbytes)
        return fut

    def flush(self) -> None:
        """Synchronously drain everything queued right now."""
        with self._cv:
            groups = self._take_locked()
        if groups:
            self.perf.inc("flush_forced")
        self._dispatch(groups)

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._worker.join(timeout=5)
        self.flush()

    # -- worker side ---------------------------------------------------------

    @staticmethod
    def _req_bytes(kind: str, mbits: np.ndarray, regions) -> int:
        # flush thresholds are tuned in PACKED bytes (see _submit)
        if kind == "planar":
            return regions.shape[1] * mbits.shape[1] // 8
        if kind == "packedbit_planes":
            # u32 plane words carry exactly 1 bit/bit: total plane bytes
            # == packed bytes (the layout's whole point)
            return int(regions.shape[0]) * int(regions.shape[1]) * 4
        return regions.nbytes

    def _take_locked(self, budget: Optional[int] = None) -> List[_Group]:
        """Detach queued work for one round.  With a `budget`, the round
        is bounded to ~budget packed bytes (whole requests; at least
        one) and the remainder STAYS QUEUED: a deep backlog becomes a
        sequence of sweet-spot-sized rounds the worker can pipeline,
        instead of one oversized dispatch that nothing overlaps with and
        that sits off the measured HBM batch optimum."""
        if budget is None:
            groups = [g for g in self._groups.values() if g.requests]
            self._groups = {}
            self._pending = 0
            self._oldest = None
            return groups
        taken: List[_Group] = []
        taken_bytes = 0
        for key in list(self._groups):
            if taken_bytes >= budget:
                break
            g = self._groups[key]
            if not g.requests:
                del self._groups[key]
                continue
            if taken_bytes + g.pending_bytes <= budget:
                taken.append(g)
                taken_bytes += g.pending_bytes
                del self._groups[key]
                continue
            # split the group: take a FIFO prefix of its requests, and
            # move the remainder to the BACK of the dict — a lane hot
            # enough to saturate every round must not starve the other
            # (matrix, kind) lanes behind it (round-robin across lanes)
            part = _Group(mbits=g.mbits, w=g.w, out_rows=g.out_rows,
                          kind=g.kind)
            while g.requests and (taken_bytes < budget
                                  or not part.requests):
                req = g.requests.pop(0)
                n = self._req_bytes(g.kind, g.mbits, req.regions)
                part.requests.append(req)
                part.pending_bytes += n
                g.pending_bytes -= n
                taken_bytes += n
            if part.requests:
                taken.append(part)
            del self._groups[key]
            if g.requests:
                self._groups[key] = g  # re-insert at tail
            break
        self._pending = sum(g.pending_bytes
                            for g in self._groups.values())
        if self._pending <= 0:
            self._oldest = None
        # else: keep _oldest — the remainder is at least as old as the
        # round just taken, so its window is already (nearly) expired and
        # the next loop iteration dispatches it immediately (pipelining)
        return taken

    def _run(self) -> None:
        # double-buffered pipeline (VERDICT r03 #4): each round's batches
        # are STAGED to the device and their computations launched (JAX
        # dispatch is async — device_put and jitted calls return before
        # the work finishes) WITHOUT blocking; the previous round's
        # results are then fetched while round N's H2D transfer and
        # compute proceed underneath.  A launched round is held in-flight
        # only while more work is already queued, so an isolated batch
        # still completes immediately.
        inflight: Optional[list] = None
        while True:
            cause = None  # why this round was cut: bytes | delay
            with self._cv:
                while not self._stop:
                    if self._pending >= self.max_pending_bytes:
                        cause = "bytes"
                        break
                    if self._oldest is not None:
                        # pending work fills its normal coalescing window
                        # even while a round is in flight — that round's
                        # compute is proceeding on-device regardless, and
                        # an eager take here would fragment batches
                        remaining = self.max_delay - (time.monotonic() - self._oldest)
                        if remaining <= 0:
                            cause = "delay"
                            break
                        self._cv.wait(timeout=remaining)
                    elif inflight is not None:
                        break  # nothing queued: fetch the in-flight round
                    else:
                        self._cv.wait()
                if self._stop:
                    if inflight is not None:
                        self._complete_safe(inflight)
                    return
                groups = self._take_locked(budget=self.max_pending_bytes)
            if groups and cause is not None:
                self.perf.inc(f"flush_{cause}")
            launched = self._launch_safe(groups)
            if inflight is not None:
                if launched:
                    self.perf.inc("overlapped_rounds")
                self._complete_safe(inflight)
                inflight = None
            with self._cv:
                more = self._pending > 0 and not self._stop
            if launched and more:
                inflight = launched  # overlap with the next round
            elif launched:
                self._complete_safe(launched)

    def _dispatch_span(self, g: _Group):
        """A span for one device dispatch: child of the first submitter's
        trace when one rode in (the OSD's `ec write`), else a root on the
        queue's own tracer; None when neither exists (tracing off)."""
        parent = next((req.span for req in g.requests
                       if req.span is not None), None)
        if parent is not None:
            sp = parent.child("ec batch dispatch")
        elif self.tracer is not None:
            sp = self.tracer.new_trace("ec batch dispatch")
        else:
            return None
        return (sp.tag("lane", g.kind)
                  .tag("group_size", len(g.requests))
                  .tag("bytes", g.pending_bytes))

    # -- circuit breaker (device-dispatch watchdog) --------------------------

    def _breaker(self, kind: str) -> _LaneBreaker:
        br = self._breakers.get(kind)
        if br is None:
            br = self._breakers[kind] = _LaneBreaker()
        return br

    def open_lanes(self) -> List[str]:
        """Lane names whose breaker is currently OPEN (serving from the
        CPU mirrors) — the BREAKER_OPEN health check's feed."""
        with self._breaker_lock:
            return [k for k, b in self._breakers.items()
                    if b.state == _LaneBreaker.OPEN]

    def _gauge_open_lanes_locked(self) -> None:
        self.perf.set("breaker_open_lanes",
                      sum(1 for b in self._breakers.values()
                          if b.state == _LaneBreaker.OPEN))

    def _breaker_route_cpu(self, kind: str) -> bool:
        """True = serve this group host-side (breaker open); False =
        dispatch to the device (closed, or the half-open probe)."""
        with self._breaker_lock:
            br = self._breakers.get(kind)
            if br is None or br.state != _LaneBreaker.OPEN:
                return False
            if time.monotonic() >= br.open_until and not br.probing:
                br.probing = True  # half-open: ONE group probes the device
                self.perf.inc("breaker_probe")
                return False
            return True

    def _breaker_failure(self, kind: str) -> None:
        with self._breaker_lock:
            br = self._breaker(kind)
            br.cooldown = (min(br.cooldown * 2, self.breaker_cooldown_max)
                           if br.cooldown else self.breaker_cooldown)
            br.state = _LaneBreaker.OPEN
            br.open_until = time.monotonic() + br.cooldown
            br.probing = False
            self.perf.inc("breaker_trip")
            self._gauge_open_lanes_locked()

    def _breaker_success(self, kind: str) -> None:
        with self._breaker_lock:
            br = self._breakers.get(kind)
            if br is None or br.state == _LaneBreaker.CLOSED:
                return
            if not br.probing:
                # a STRAGGLER from before the trip completing fine must
                # not close the breaker (and zero the escalating
                # cooldown) — only the designated half-open probe is
                # evidence about the lane's CURRENT health
                return
            br.state = _LaneBreaker.CLOSED
            br.cooldown = 0.0
            br.probing = False
            self.perf.inc("breaker_recover")
            self._gauge_open_lanes_locked()

    def _complete_cpu(self, g: _Group, wait_s: float = 0.0) -> None:
        """Serve a whole group on the host CPU mirrors (breaker open, or
        rescue after a device failure): every request resolves with the
        byte-identical numpy result.  A CPU-path error fails the group's
        futures like any dispatch error would."""
        t0 = time.monotonic()
        try:
            results = [
                _cpu_apply_request(g.kind, g.mbits, req.regions, g.w,
                                   g.out_rows)
                for req in g.requests
            ]
        except Exception as e:
            self._fail_group(g, e)
            return
        for req, res in zip(g.requests, results):
            try:
                req.future.set_result(res)
            except InvalidStateError:
                pass
        self.perf.inc("breaker_fallback")
        self.timeline.append({
            "ts": time.time(), "lane": g.kind,
            "group_size": len(g.requests),
            "bytes": g.pending_bytes,
            "queue_wait_s": round(wait_s, 6),
            "device_s": round(time.monotonic() - t0, 6),
            "cpu_fallback": True})

    def _launch_safe(self, groups: List[_Group]) -> list:
        launched = []
        for g in groups:
            if not g.requests:
                continue
            now = time.monotonic()
            # queue-wait: how long each request coalesced before launch
            wait_s = 0.0
            for req in g.requests:
                w = now - req.t_submit
                self.perf.tinc("queue_wait", w)
                wait_s += w
                if req.span is not None:
                    req.span.event(f"ec coalesced lane={g.kind} "
                                   f"group={len(g.requests)}")
            wait_s /= len(g.requests)
            if self._breaker_route_cpu(g.kind):
                # lane breaker open: the device is sick — serve the whole
                # group host-side, byte-identical
                self._complete_cpu(g, wait_s)
                continue
            sp = self._dispatch_span(g)
            if self.inject_dispatch_delay:
                # osd_debug_inject_dispatch_delay: counted into the
                # dispatch elapsed (t_launch = now, above) so the
                # watchdog sees the slow dispatch
                time.sleep(self.inject_dispatch_delay)
            try:
                if g.kind == "planar":
                    state = self._launch_planar(g)
                elif g.kind == "resident":
                    state = self._launch_resident(g)
                elif g.kind == "packedbit":
                    state = self._launch_packedbit(g)
                elif g.kind == "packedbit_resident":
                    state = self._launch_packedbit_resident(g)
                elif g.kind == "packedbit_planes":
                    state = self._launch_packedbit_planes(g)
                else:
                    state = self._launch_packed(g)
                if sp is not None:
                    sp.event("launched")
                launched.append(_Launched(g, state, now, sp, wait_s))
            except Exception as e:
                # device launch failure: trip the breaker and RESCUE the
                # group host-side — submitters never see the device die
                if sp is not None:
                    sp.event(f"launch failed: {type(e).__name__}")
                    sp.finish()
                self._breaker_failure(g.kind)
                self._complete_cpu(g, wait_s)
        if launched and self._launch_hook is not None:
            self._launch_hook()
        return launched

    def _complete_safe(self, launched: list) -> None:
        for lc in launched:
            g, state = lc.group, lc.state
            try:
                if g.kind == "planar":
                    self._complete_planar(g, state)
                elif g.kind == "resident":
                    self._complete_resident(g, state)
                elif g.kind == "packedbit_resident":
                    self._complete_packedbit_resident(g, state)
                elif g.kind == "packedbit_planes":
                    self._complete_packedbit_planes(g, state)
                else:
                    # "packed" and "packedbit": both fan packed uint8
                    # byte columns back out
                    self._complete_packed(g, state)
            except Exception as e:
                # device completion failure: trip the breaker and rescue
                # the group host-side (byte-identical CPU mirrors)
                if lc.span is not None:
                    lc.span.event(f"complete failed: {type(e).__name__}")
                    lc.span.finish()
                self._breaker_failure(g.kind)
                self._complete_cpu(g, lc.wait_s)
                continue
            device_s = time.monotonic() - lc.t_launch
            if self.dispatch_timeout and device_s > self.dispatch_timeout:
                # the dispatch COMPLETED (results are good) but blew the
                # watchdog budget: the lane is sick — trip so the next
                # groups take the CPU path until a probe proves it healthy
                self._breaker_failure(g.kind)
            else:
                self._breaker_success(g.kind)
            self.perf.tinc("dispatch_dev", device_s)
            self.perf.hinc("group_size", len(g.requests))
            if lc.span is not None:
                lc.span.event("fan-out")
                lc.span.finish()
            for req in g.requests:
                if req.span is not None:
                    req.span.event(f"ec fan-out lane={g.kind}")
            self.timeline.append({
                "ts": time.time(), "lane": g.kind,
                "group_size": len(g.requests),
                "bytes": g.pending_bytes,
                "queue_wait_s": round(lc.wait_s, 6),
                "device_s": round(device_s, 6)})

    @staticmethod
    def _fail_group(g: _Group, e: Exception) -> None:
        for req in g.requests:
            try:
                req.future.set_exception(e)
            except InvalidStateError:
                pass

    def _dispatch(self, groups: List[_Group]) -> None:
        # synchronous drain (flush()/close()): launch then complete
        self._complete_safe(self._launch_safe(groups))

    def _note_dispatch(self, nbytes: int, sharded: bool) -> None:
        """Dispatch-complete accounting shared by every lane."""
        self.perf.inc("dispatch")
        if sharded:
            self.perf.inc("sharded_dispatch")
        self.perf.inc("bytes", nbytes)


    def _maybe_shard(self, batch, pad_np: bool, align: int = 1):
        """Lay a dispatch batch across the mesh when one is attached.
        Columns pad out to a device-grid multiple (bucket_columns gives
        powers of two, which a 6-device grid would never divide) — the
        pad is zeros beyond every request's slice, so fan-out offsets
        are unaffected.  `align` additionally rounds the padded width to
        a multiple of lcm(grid, align): the packed-bit lanes need whole
        u32 words per plane row (align=32) even after grid padding.
        Returns (batch, sharded)."""
        if self.mesh is None:
            return batch, False
        try:
            want = self.mesh.pad_cols(batch.shape[1])
            if align > 1:
                import math

                lcm = (align * self.mesh.n_devices
                       // math.gcd(align, self.mesh.n_devices))
                want = -(-want // lcm) * lcm
            if want != batch.shape[1]:
                extra = want - batch.shape[1]
                if pad_np:
                    batch = np.pad(batch, ((0, 0), (0, extra)))
                else:
                    import jax.numpy as jnp

                    batch = jnp.pad(batch, ((0, 0), (0, extra)))
            return self.mesh.shard_batch(batch), True
        except Exception:
            return batch, False  # sick mesh: single-device still serves

    def _stage_packed_batch(self, g: _Group, align: int = 1):
        """The shared launch preamble for packed-byte request groups:
        coalesce the requests column-wise, pow2-bucket the width (bounds
        XLA recompiles), shard across the mesh when one is attached, and
        otherwise start the H2D transfer NOW so it overlaps the previous
        round's result fetch.  Returns (widths, batch, sharded, nbytes)."""
        import jax

        from ceph_tpu.ops.gf2 import bucket_columns as _bucket

        widths = [req.regions.shape[1] for req in g.requests]
        batch = np.concatenate([req.regions for req in g.requests], axis=1)
        pad = _bucket(batch.shape[1]) - batch.shape[1]
        if pad:
            batch = np.pad(batch, ((0, 0), (0, pad)))
        nbytes = batch.nbytes
        batch, sharded = self._maybe_shard(batch, pad_np=True, align=align)
        if not sharded:
            batch = jax.device_put(batch)  # async H2D staging
        return widths, batch, sharded, nbytes

    def _launch_packed(self, g: _Group):
        from ceph_tpu.ops.gf2 import gf2_apply_bytes

        widths, batch, sharded, nbytes = self._stage_packed_batch(g)
        use_pallas = self._use_pallas and not sharded
        if use_pallas is None:
            from ceph_tpu.ops.gf2 import pallas_enabled
            from ceph_tpu.ops.pallas_gf2 import TILE_B
            from ceph_tpu.utils.jaxdev import probe_backend

            # pallas_call does not run under GSPMD sharding (it would
            # need a shard_map wrapper); sharded batches take XLA
            use_pallas = (
                not sharded
                and pallas_enabled()
                and probe_backend() == "tpu"
                and batch.shape[1] % TILE_B == 0
            )
        # async launch: the jitted call returns a device handle
        out = gf2_apply_bytes(g.mbits, batch, g.w, g.out_rows,
                              use_pallas=use_pallas)
        return widths, out, sharded, nbytes

    def _complete_packed(self, g: _Group, state) -> None:
        widths, out, sharded, nbytes = state
        out = np.asarray(out)  # blocks until compute + D2H done
        self._note_dispatch(nbytes, sharded)
        off = 0
        for width, req in zip(widths, g.requests):
            # a submitter may have been CANCELLED while waiting (an
            # async op torn down mid-flight propagates cancellation
            # into the future via asyncio.wrap_future): its slice is
            # simply dropped
            try:
                # copy: a view would pin the whole batch buffer for as
                # long as any single result stays alive
                req.future.set_result(out[:, off : off + width].copy())
            except InvalidStateError:
                pass  # cancelled in the check-to-set window
            off += width

    def _launch_planar(self, g: _Group):
        """Matmul-only dispatch over HBM-resident bit-planes: ONE batched
        device call per (matrix) group; results are handed back as planar
        device buffers so the next stage chains without a host bounce."""
        import jax.numpy as jnp

        from ceph_tpu.ops.gf2 import bucket_columns as _bucket
        from ceph_tpu.ops.gf2 import gf2_matmul

        widths = [req.regions.shape[1] for req in g.requests]
        batch = (g.requests[0].regions if len(g.requests) == 1
                 else jnp.concatenate([req.regions
                                       for req in g.requests], axis=1))
        # pow2 column bucketing, same as the other lanes: varying
        # coalesced widths must not each compile a fresh gf2_matmul
        pad = _bucket(batch.shape[1]) - batch.shape[1]
        if pad:
            batch = jnp.pad(batch, ((0, 0), (0, pad)))
        batch, sharded = self._maybe_shard(batch, pad_np=False)
        out = gf2_matmul(jnp.asarray(g.mbits), batch)
        return widths, out, sharded

    def _complete_planar(self, g: _Group, state) -> None:
        widths, out, sharded = state
        self._note_dispatch(
            sum(w for w in widths) * g.mbits.shape[1] // 8, sharded)
        off = 0
        for width, req in zip(widths, g.requests):
            try:
                # device-side slice: stays planar-resident; no host copy
                req.future.set_result(out[:, off : off + width])
            except InvalidStateError:
                pass
            off += width

    def _launch_resident(self, g: _Group):
        """Residency write path: ONE fused batched call — unpack the
        concatenated packed rows, matmul, pack the parity — and fan both
        products out per request: (packed parity for persistence, planar
        rows to stay HBM-resident)."""
        from ceph_tpu.ops.gf2 import gf2_encode_resident

        widths, batch, sharded, nbytes = self._stage_packed_batch(g)
        # AFTER any mesh grid-padding: the planar fan-out factor must
        # relate all_bits' columns to the columns the matmul actually saw
        cols = batch.shape[1]
        packed, all_bits = gf2_encode_resident(
            g.mbits, batch, g.w, g.out_rows)
        return widths, packed, all_bits, sharded, nbytes, cols

    def _complete_resident(self, g: _Group, state) -> None:
        widths, packed, all_bits, sharded, nbytes, cols = state
        packed = np.asarray(packed)  # blocks until ready
        self._note_dispatch(nbytes, sharded)
        # planar columns per packed byte-column depends on w (w=16: B//2)
        cfac = all_bits.shape[1] / cols
        off = 0
        for width, req in zip(widths, g.requests):
            try:
                c0, c1 = int(off * cfac), int((off + width) * cfac)
                req.future.set_result((packed[:, off : off + width].copy(),
                                   all_bits[:, c0:c1]))
            except InvalidStateError:
                pass
            off += width

    # -- packed-bit lanes (u32 plane words + static XOR schedules) -----------

    def _launch_packedbit(self, g: _Group):
        """One fused schedule call over the coalesced packed rows:
        unpack -> u32 words -> XOR schedule -> byte pack, compiled per
        matrix behind the gf2 LRU.  Fan-out is byte columns, so requests
        of ANY width coalesce (pow2 bucketing keeps B % 32 == 0)."""
        from ceph_tpu.ops.gf2 import gf2_apply_packedbit

        widths, batch, sharded, nbytes = self._stage_packed_batch(g, align=32)
        out = gf2_apply_packedbit(g.mbits, batch)
        return widths, out, sharded, nbytes

    # completion: _complete_packed (identical packed-byte fan-out)

    def _launch_packedbit_resident(self, g: _Group):
        """Packed-bit residency write path: one fused batched call, both
        products fanned out per request — packed parity bytes for
        persistence, u32 plane words to stay HBM-resident.  Request
        widths must be whole u32 words (B % 32 == 0) so the plane
        fan-out slices stay word-aligned; submit_packedbit_resident
        rejects misaligned requests before they can coalesce."""
        from ceph_tpu.ops.gf2 import gf2_encode_packedbit_resident

        widths, batch, sharded, nbytes = self._stage_packed_batch(g, align=32)
        packed, planes = gf2_encode_packedbit_resident(g.mbits, batch)
        return widths, packed, planes, sharded, nbytes

    def _complete_packedbit_resident(self, g: _Group, state) -> None:
        # DONATION SAFETY: every fan-out below is a device-side SLICE of
        # the one batched `planes` product — consumers (the pagestore's
        # device-arm install, ceph_tpu/ops/slab.py) must never donate
        # the DATA argument of their kernels, because sibling requests
        # alias the same underlying buffer; only the slab argument,
        # which this plane never hands out, is donatable.
        widths, packed, planes, sharded, nbytes = state
        packed = np.asarray(packed)  # blocks until ready
        self._note_dispatch(nbytes, sharded)
        if len(g.requests) == 1 and packed.shape[1] == widths[0]:
            # single-request group covering the full (unpadded) batch:
            # hand the whole product back — no slice op on the device
            # graph, and the install's flatten sees one contiguous
            # buffer
            try:
                g.requests[0].future.set_result((packed, planes))
            except InvalidStateError:
                pass
            return
        off = 0
        for width, req in zip(widths, g.requests):
            try:
                # 32 byte columns per u32 plane word (integer exact: the
                # launch asserted width % 32 == 0)
                req.future.set_result((packed[:, off : off + width].copy(),
                                   planes[:, off // 32 : (off + width) // 32]))
            except InvalidStateError:
                pass
            off += width

    def _launch_packedbit_planes(self, g: _Group):
        """Schedule-only dispatch over resident u32 plane words — the
        packed-bit mirror of the planar lane: results stay device-side
        plane buffers, chaining without a host bounce."""
        import jax.numpy as jnp

        from ceph_tpu.ops.gf2 import bucket_columns as _bucket
        from ceph_tpu.ops.gf2 import gf2_xor_packed

        widths = [req.regions.shape[1] for req in g.requests]  # u32 words
        batch = (g.requests[0].regions if len(g.requests) == 1
                 else jnp.concatenate([req.regions
                                       for req in g.requests], axis=1))
        # pow2 word bucketing (lo=32 words == the byte lanes' 1024 cols)
        pad = _bucket(batch.shape[1], lo=32) - batch.shape[1]
        if pad:
            batch = jnp.pad(batch, ((0, 0), (0, pad)))
        batch, sharded = self._maybe_shard(batch, pad_np=False)
        out = gf2_xor_packed(g.mbits, batch)
        return widths, out, sharded

    def _complete_packedbit_planes(self, g: _Group, state) -> None:
        widths, out, sharded = state
        # u32 plane words carry 1 bit/bit, so plane bytes == packed-
        # equivalent bytes (same arithmetic as _req_bytes: C rows x Wc
        # words x 4 B/word; no 8x int8 expansion to divide back out)
        self._note_dispatch(sum(widths) * 4 * g.mbits.shape[1], sharded)
        off = 0
        for width, req in zip(widths, g.requests):
            try:
                req.future.set_result(out[:, off : off + width])  # stays resident
            except InvalidStateError:
                pass
            off += width


class PlanarShardStore:
    """HBM-resident planar shard cache — the residency manager behind the
    measured ~1.6x pack-elimination win (ceph_tpu/ops/gf2.py writeup).

    Rows of packed uint8 shard bytes are admitted ONCE (one on-device
    unpack) and then live in HBM as int8 bit-planes; every subsequent EC
    op on them — encode, decode-reconstruct, scrub re-encode, recovery —
    is a pure GF(2) matmul chaining planar buffers, and bytes are packed
    back exactly once, when they leave for the wire/store.  The
    reference's analog is the stripe buffer staying cache-resident across
    ECUtil::encode's loop (reference src/osd/ECUtil.cc:123-160); here the
    residency scope is HBM across whole pipeline stages.

    Capacity is a hard byte budget over the PLANAR footprint (w x the
    packed bytes): least-recently-used entries are evicted, so the store
    degrades to the packed path, never to an OOM.  Thread-safe — the OSD
    event loop, the batching worker, and tests may touch it concurrently.
    """

    def __init__(self, capacity_bytes: int = 256 << 20,
                 queue: Optional[BatchingQueue] = None):
        from ceph_tpu.common.lockdep import make_mutex

        self.capacity_bytes = capacity_bytes
        self.queue = queue
        self._lock = make_mutex("planar-store")
        self._entries: "OrderedDict[Any, Any]" = OrderedDict()
        self._bytes: Dict[Any, int] = {}
        self._trim: Dict[Any, int] = {}  # packedbit admits: pre-pad width
        # exit-boundary memo: key -> (version, packed host result).  The
        # store's contract is "pack exactly once per resident lifetime",
        # but a cache-tier resident is READ many times — without a memo
        # every resident-hit read re-pays the device pack.  Lives and
        # dies WITH the entry (cleared on put/drop/LRU-evict), so a
        # memo can never outlive or contradict its resident.  Host RAM,
        # not HBM — tracked separately (memo_bytes gauge) and capped at
        # the store's capacity so the total footprint the operator
        # budgets for is at most 2x capacity_bytes, never unbounded.
        self._memo: Dict[Any, Tuple[Any, Any]] = {}
        self.memo_bytes = 0
        self.resident_bytes = 0
        self.admits = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # the `planar_store` perf set mirrors the bare ints above (kept:
        # eviction logic and tests read them) and adds the boundary
        # latencies the ints can't carry (module-docstring schema)
        self.perf = (
            PerfCountersBuilder("planar_store")
            .add_u64_counter("admit", "packed rows admitted (one unpack)")
            .add_u64_counter("hit", "resident lookups served")
            .add_u64_counter("miss", "lookups that fell to the packed path")
            .add_u64_counter("evict", "LRU evictions under the byte budget")
            .add_u64("resident_bytes", "planar HBM footprint (gauge)")
            .add_u64("entries", "resident objects (gauge)")
            .add_u64("memo_bytes",
                     "exit-boundary packed memo host footprint (gauge)")
            .add_time_avg("pack_s",
                          "device->host pack seconds at the exit boundary")
            .add_time_avg("unpack_s",
                          "host->device unpack seconds at admission")
            .create_perf_counters())
        # `perf reset` re-reads the live gauges instead of leaving the
        # residency footprint misreported as 0 until the next admit
        self.perf.resync = self._resync_gauges

    def _resync_gauges(self) -> None:
        # gauges are written INSIDE the store lock everywhere (here,
        # put_planar, drop): an unlocked write could overwrite a newer
        # value with a stale snapshot.  Lock order is store -> perf.
        with self._lock:
            self.perf.set("resident_bytes", self.resident_bytes)
            self.perf.set("entries", len(self._entries))
            self.perf.set("memo_bytes", self.memo_bytes)

    # -- host boundary (pack/unpack paid here, once) -------------------------

    def admit(self, key: Any, rows: np.ndarray, w: int = 8,
              meta: Any = None, layout: str = "planes"):
        """Unpack packed [n, B] uint8 rows onto the device and keep them
        resident under `key`.  Returns the resident device buffer.
        layout="planes" stores int8 bit-planes (any w); "packedbit"
        stores u32 plane words (w=8 only, 1/8th the footprint — the
        production lane), padding B out to whole words and trimming on
        read."""
        with self.perf.time_avg("unpack_s"):
            if layout == "packedbit":
                from ceph_tpu.ops.gf2 import to_packedbit

                assert w == 8, "packed-bit residency is the w=8 byte layout"
                B = rows.shape[1]
                buf = np.ascontiguousarray(rows)
                if B % 32:
                    buf = np.pad(buf, ((0, 0), (0, 32 - B % 32)))
                bits = to_packedbit(buf)
                self.put_planar(key, bits, w=w, n_rows=rows.shape[0],
                                meta=meta, trim=B)
            else:
                from ceph_tpu.ops.gf2 import to_planar

                bits = to_planar(np.ascontiguousarray(rows), w)
                self.put_planar(key, bits, w=w, n_rows=rows.shape[0],
                                meta=meta)
        self.admits += 1
        self.perf.inc("admit")
        return bits

    def read(self, key: Any) -> Optional[np.ndarray]:
        """Pack the resident rows back to [n, B] uint8 host bytes — the
        EXIT boundary.  None when not resident.  Handles both layouts
        (entry dtype tells them apart: uint32 words vs int8 planes)."""
        got = self.get_planar(key)
        if got is None:
            return None
        bits, w, n_rows, _meta = got
        if np.dtype(bits.dtype) == np.uint32:
            from ceph_tpu.ops.gf2 import from_packedbit

            with self.perf.time_avg("pack_s"):
                out = np.asarray(from_packedbit(bits, n_rows))
            with self._lock:
                trim = self._trim.get(key)
            return out if trim is None else out[:, :trim]
        from ceph_tpu.ops.gf2 import from_planar

        with self.perf.time_avg("pack_s"):
            return np.asarray(from_planar(bits, w, n_rows))

    # -- resident side (no pack/unpack anywhere below) -----------------------

    def put_planar(self, key: Any, bits, w: int = 8,
                   n_rows: Optional[int] = None, meta: Any = None,
                   trim: Optional[int] = None) -> None:
        """`meta` is caller state carried with the entry (the OSD stores
        the object VERSION there, so a read can reject a stale resident).
        `trim` is the pre-pad byte width of a packed-bit admit, installed
        under the same lock as the entry so a concurrent read never sees
        the entry without its trim."""
        if n_rows is None:
            n_rows = bits.shape[0] // w
        # HBM footprint by element width: int8 planes are 1 B/element
        # (8x the packed bytes), u32 packed-bit words 4 B/element (1x)
        nbytes = int(np.prod(bits.shape)) * np.dtype(bits.dtype).itemsize
        with self._lock:
            if key in self._entries:
                self.resident_bytes -= self._bytes[key]
            self._entries[key] = (bits, w, n_rows, meta)
            self._entries.move_to_end(key)
            self._bytes[key] = nbytes
            self._memo_discard(key)  # new rows: stale packed memo dies
            if trim is None:
                self._trim.pop(key, None)  # re-put resets admit-time trim
            else:
                self._trim[key] = trim
            self.resident_bytes += nbytes
            evicted = 0
            while self.resident_bytes > self.capacity_bytes and self._entries:
                old_key, _ = self._entries.popitem(last=False)
                self.resident_bytes -= self._bytes.pop(old_key)
                self._trim.pop(old_key, None)
                self._memo_discard(old_key)
                self.evictions += 1
                evicted += 1
            # gauge writes stay under the store lock (see _resync_gauges)
            self.perf.set("resident_bytes", self.resident_bytes)
            self.perf.set("entries", len(self._entries))
        if evicted:
            self.perf.inc("evict", evicted)

    def get_planar(self, key: Any):
        """(bits, w, n_rows, meta) or None; refreshes LRU position."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
        self.perf.inc("hit" if ent is not None else "miss")
        return ent

    # -- the residency protocol shared with PagedResidentStore ---------------
    # (ceph_tpu/rados/pagestore.py): ecutil's planar_* helpers and the
    # OSD tier paths speak these four shapes so either store can sit
    # behind the cache tier.

    def touch(self, key: Any):
        """(w, n_rows, meta) with LRU refresh + hit/miss counting,
        materializing nothing."""
        ent = self.get_planar(key)
        return None if ent is None else (ent[1], ent[2], ent[3])

    def entry_info(self, key: Any):
        """(w, n_rows, meta) without LRU/counter side effects."""
        with self._lock:
            ent = self._entries.get(key)
        return None if ent is None else (ent[1], ent[2], ent[3])

    def resident_meta(self, key: Any):
        """The entry's caller meta, or None — the policy probe shape."""
        info = self.entry_info(key)
        return None if info is None else info[2]

    def gather_rows(self, key: Any, r0: int, r1: int):
        """The resident's bit-rows [r0, r1) (a device-buffer slice
        here; the paged store gathers from its page table), or None.
        No LRU side effects — ``touch`` owns those."""
        with self._lock:
            ent = self._entries.get(key)
        if ent is None or r1 > ent[0].shape[0]:
            return None
        return ent[0][r0:r1]

    def apply(self, key: Any, mbits: np.ndarray, out_rows: int,
              out_key: Any = None):
        """Apply a bit-matrix to the resident planar rows (encode with a
        generator, reconstruct with an inverted signature matrix, scrub
        re-encode, ...).  Pure matmul; the result stays planar, stored
        under `out_key` when given.  Returns the planar device buffer, or
        None when `key` is not resident.  Routes through the batching
        queue when one is attached (cross-object coalescing)."""
        got = self.get_planar(key)
        if got is None:
            return None
        bits, w, _, _meta = got
        if np.dtype(bits.dtype) == np.uint32:
            # packed-bit resident: the matrix runs as a static XOR
            # schedule over the u32 plane words (compiled per matrix
            # behind the gf2 LRU — decode signatures included)
            mb = np.asarray(mbits, dtype=np.uint8)
            if self.queue is not None:
                out = self.queue.submit_packedbit_planes(
                    mb, bits, w, out_rows).result()
            else:
                from ceph_tpu.ops.gf2 import gf2_xor_packed

                out = gf2_xor_packed(mb, bits)
        elif self.queue is not None:
            out = self.queue.submit_planar(
                np.asarray(mbits), bits, w, out_rows).result()
        else:
            import jax.numpy as jnp

            from ceph_tpu.ops.gf2 import gf2_matmul

            out = gf2_matmul(jnp.asarray(np.asarray(mbits)), bits)
        if out_key is not None:
            self.put_planar(out_key, out, w=w, n_rows=out_rows)
        return out

    def drop(self, key: Any, force: bool = False) -> bool:
        """Remove `key` if resident; True when an entry was actually
        dropped.  Dropping an absent key is a supported no-op (the tier
        agent races the LRU here: either side may have evicted first,
        and the loser must count a no-op, not error).  ``force`` is the
        paged store's dirty-override knob — a no-op here, where nothing
        is ever dirty — accepted so callers can speak one surface."""
        with self._lock:
            dropped = key in self._entries
            if dropped:
                del self._entries[key]
                self.resident_bytes -= self._bytes.pop(key)
                self._trim.pop(key, None)
            self._memo_discard(key)
            self.perf.set("resident_bytes", self.resident_bytes)
            self.perf.set("entries", len(self._entries))
        return dropped

    def peek(self, key: Any):
        """(bits, w, n_rows, meta) or None WITHOUT touching LRU order or
        the hit/miss counters — policy probes (the tier promotion gate
        asking "already resident at this version?") must not make an
        entry look recently used or pollute the hit ratio."""
        with self._lock:
            return self._entries.get(key)

    def entries_snapshot(self) -> List[Tuple[Any, int]]:
        """(key, planar nbytes) pairs in LRU order, oldest first — the
        tier agent's eviction-candidate input.  A point-in-time copy:
        the agent ranks against it and tolerates entries that vanish
        before its drop lands (drop() reports the no-op)."""
        with self._lock:
            return [(k, self._bytes[k]) for k in self._entries]

    def _memo_discard(self, key: Any) -> None:
        """Drop a key's memo and its byte accounting.  Caller holds the
        store lock."""
        got = self._memo.pop(key, None)
        if got is not None:
            self.memo_bytes -= len(got[1])

    def memo_get(self, key: Any, version: Any):
        """The exit-boundary memo for `key` at `version`, or None.  Only
        valid while the entry is RESIDENT (callers validate residency
        via get_planar first); the memo is version-tagged so a re-put at
        a newer version can never serve yesterday's bytes."""
        with self._lock:
            if key not in self._entries:
                return None
            got = self._memo.get(key)
        if got is None or got[0] != version:
            return None
        return got[1]

    def memo_put(self, key: Any, version: Any, value: Any) -> None:
        """Record the packed host result of this resident at `version`
        (one entry per key, latest version wins): subsequent resident
        hits skip the device pack entirely — the 'pack once per
        resident lifetime' contract made true under repeated reads.
        Ignored when the entry is not resident (a drop/evict raced the
        pack: the memo must not outlive the entry), and when the memo
        pool is at its budget (capacity_bytes: host RAM stays the same
        order as the HBM budget, so the operator's total footprint is
        bounded by ~2x capacity — a refused memo only costs a re-pack
        on the next read, never correctness)."""
        nbytes = len(value)
        with self._lock:
            if key not in self._entries:
                return
            self._memo_discard(key)
            if self.memo_bytes + nbytes > self.capacity_bytes:
                self.perf.set("memo_bytes", self.memo_bytes)
                return
            self._memo[key] = (version, value)
            self.memo_bytes += nbytes
            self.perf.set("memo_bytes", self.memo_bytes)

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def stats(self) -> Dict[str, int]:
        return {"resident_bytes": self.resident_bytes,
                "memo_bytes": self.memo_bytes,
                "entries": len(self._entries), "admits": self.admits,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}
