"""Stripe-batching dispatch queue — amortizing many small EC ops into one
device call.

The reference dispatches its codec once per 4 KiB-unit stripe inside
ECUtil::encode (reference src/osd/ECUtil.cc:123-160) and per 1 MiB buffer in
the benchmark; a TPU dispatch has fixed launch latency, so the >=10x target
"lives or dies on the batching queue" (SURVEY.md §7 hard part 2).  This
queue aggregates encode/decode requests from many objects/ops, concatenates
them column-wise into one [rows, sum(B)] buffer per (matrix, layout) group,
runs ONE bit-plane matmul, and fans completions back out — the same
submit -> aggregate -> dispatch -> completion-fan-out pipeline ECBackend's
write path drives (submit_transaction -> ... -> try_reads_to_commit,
ECBackend.cc:1525->1989).

Threading model: submit() is non-blocking and returns a Future; a worker
thread flushes when pending bytes cross `max_pending_bytes` or `max_delay`
elapses, whichever first.  flush() forces a synchronous drain (used by
tests and by the benchmark's timed sections).

BIT-PLANAR RESIDENCY (the measured ~1.6x win, ceph_tpu/ops/gf2.py
writeup): `submit_planar` dispatches over shards that already live in HBM
as int8 bit-planes — matmul only, no unpack/pack — and resolves to planar
device buffers, so encode -> decode -> recovery chain on-device.
`PlanarShardStore` is the residency manager: an LRU-bounded HBM cache of
planar shard rows where bytes pay the pack/unpack boundary exactly once,
when they enter or leave the device tier (the reference's analog is the
buffer staying in L2/registers across ECUtil::encode's per-stripe loop,
reference src/osd/ECUtil.cc:123-160; on a TPU the "stay resident" scope
is HBM across whole pipeline stages).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class _Group:
    mbits: np.ndarray
    w: int
    out_rows: int
    # dispatch lane: "packed" (unpack+matmul+pack fused per dispatch),
    # "planar" (matmul-only over resident bit-planes), "resident"
    # (packed in -> packed parity + planar rows out, the write path)
    kind: str = "packed"
    requests: List[Tuple[Any, Future]] = field(default_factory=list)
    pending_bytes: int = 0


class BatchingQueue:
    def __init__(
        self,
        # 16 MiB/dispatch: the measured HBM sweet spot for the planar
        # pipeline (bench.py r4 sweep — the 8x bit-plane expansion makes
        # 64 MiB batches HBM-bound on v5e; 2 MiB of columns at k=8 wins)
        max_pending_bytes: int = 16 << 20,
        max_delay: Optional[float] = None,
        use_pallas: Optional[bool] = None,
        mesh=None,
    ):
        import os as _os

        self.max_pending_bytes = max_pending_bytes
        # the DEFAULT coalescing window is tunable (CEPH_TPU_BATCH_DELAY
        # seconds): loaded CI hosts widen it so coalescing tests assert
        # the MECHANISM rather than the 2ms default's luck.  An explicit
        # max_delay argument always wins, and a malformed value falls
        # back rather than crashing the first EC write.
        if max_delay is None:
            try:
                max_delay = float(
                    _os.environ.get("CEPH_TPU_BATCH_DELAY") or 0.002)
            except ValueError:
                max_delay = 0.002
        self.max_delay = max_delay
        self._use_pallas = use_pallas
        # device-mesh execution (ceph_tpu/parallel/mesh.py): when a mesh
        # is attached (or auto-engages on a multi-chip backend), every
        # dispatch lane lays its batch out across the mesh's column axis
        # — the same compiled ops run SPMD over all devices, collectives
        # inserted by XLA where a consumer needs them.  mesh=None means
        # auto-detect; mesh=False pins the queue single-device (bench
        # arms and n=1 dryruns that must not auto-engage).
        if mesh is None:
            from ceph_tpu.parallel.mesh import shared_mesh

            mesh = shared_mesh()
        self.mesh = mesh or None
        self.sharded_dispatches = 0  # dispatches that ran across the mesh
        # rounds whose H2D+launch overlapped the previous round's
        # result fetch (the double-buffering VERDICT r03 #4 asks for)
        self.overlapped_rounds = 0
        # test seam: invoked (worker thread) after a round is launched,
        # before the backlog check — lets tests inject a standing backlog
        # deterministically instead of racing thread schedulers
        self._launch_hook = None
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._groups: Dict[Tuple, _Group] = {}
        self._pending = 0
        self._oldest: Optional[float] = None
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True, name="ec-batch")
        self._worker.start()
        self.dispatches = 0  # perf counter: device calls issued
        self.bytes_dispatched = 0
        self.submits = 0  # requests accepted (ops/dispatch = submits/dispatches)

    # -- client side ---------------------------------------------------------

    def submit(
        self, mbits: np.ndarray, regions: np.ndarray, w: int, out_rows: int
    ) -> "Future[np.ndarray]":
        """Queue (mbits @ regions) over the byte layout; resolves to the
        [out_rows, B] parity/reconstruction buffer."""
        return self._submit(mbits, regions, w, out_rows, "packed")

    def submit_planar(
        self, mbits: np.ndarray, bits, w: int, out_rows: int
    ) -> "Future[object]":
        """Queue (mbits @ bits) over ALREADY-PLANAR device bit-planes
        ([rows*w, Bcols] int8); resolves to the [out_rows*w, Bcols] planar
        device buffer — no pack, the result stays HBM-resident for the
        next pipeline stage."""
        return self._submit(mbits, bits, w, out_rows, "planar")

    def submit_resident(
        self, mbits: np.ndarray, rows: np.ndarray, w: int, out_rows: int
    ) -> "Future[object]":
        """The residency WRITE path: packed [n, B] uint8 rows in, ONE
        fused batched device call (unpack + matmul + parity pack), and
        the future resolves to (packed_parity np [out_rows, B],
        all_bits planar [(n+out_rows)*w, Bc]) — parity bytes for
        persistence, planar rows to keep HBM-resident.  Submission is
        non-blocking (no device work on the caller's thread), so
        concurrent ops coalesce exactly like the packed lane."""
        return self._submit(mbits, rows, w, out_rows, "resident")

    def _submit(self, mbits, regions, w, out_rows, kind) -> Future:
        fut: Future = Future()
        # the full dispatch signature: identical matrix BYTES under a
        # different w or output arity is a different computation; the
        # three lanes never share a dispatch (different layouts)
        key = (w, out_rows, kind, mbits.shape, mbits.tobytes())
        with self._cv:
            if self._stop:
                raise RuntimeError("BatchingQueue is closed")
            group = self._groups.get(key)
            if group is None:
                group = self._groups[key] = _Group(
                    mbits=mbits, w=w, out_rows=out_rows, kind=kind)
            group.requests.append((regions, fut))
            self.submits += 1
            # planar bit-plane submissions are 8x-expanded int8: count
            # their packed-equivalent size or the lane would flush at 1/8
            # the measured batch sweet spot
            nbytes = self._req_bytes(kind, mbits, regions)
            group.pending_bytes += nbytes
            self._pending += nbytes
            if self._oldest is None:
                self._oldest = time.monotonic()
            self._cv.notify()
        return fut

    def flush(self) -> None:
        """Synchronously drain everything queued right now."""
        with self._cv:
            groups = self._take_locked()
        self._dispatch(groups)

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._worker.join(timeout=5)
        self.flush()

    # -- worker side ---------------------------------------------------------

    @staticmethod
    def _req_bytes(kind: str, mbits: np.ndarray, regions) -> int:
        # flush thresholds are tuned in PACKED bytes (see _submit)
        return (regions.shape[1] * mbits.shape[1] // 8
                if kind == "planar" else regions.nbytes)

    def _take_locked(self, budget: Optional[int] = None) -> List[_Group]:
        """Detach queued work for one round.  With a `budget`, the round
        is bounded to ~budget packed bytes (whole requests; at least
        one) and the remainder STAYS QUEUED: a deep backlog becomes a
        sequence of sweet-spot-sized rounds the worker can pipeline,
        instead of one oversized dispatch that nothing overlaps with and
        that sits off the measured HBM batch optimum."""
        if budget is None:
            groups = [g for g in self._groups.values() if g.requests]
            self._groups = {}
            self._pending = 0
            self._oldest = None
            return groups
        taken: List[_Group] = []
        taken_bytes = 0
        for key in list(self._groups):
            if taken_bytes >= budget:
                break
            g = self._groups[key]
            if not g.requests:
                del self._groups[key]
                continue
            if taken_bytes + g.pending_bytes <= budget:
                taken.append(g)
                taken_bytes += g.pending_bytes
                del self._groups[key]
                continue
            # split the group: take a FIFO prefix of its requests, and
            # move the remainder to the BACK of the dict — a lane hot
            # enough to saturate every round must not starve the other
            # (matrix, kind) lanes behind it (round-robin across lanes)
            part = _Group(mbits=g.mbits, w=g.w, out_rows=g.out_rows,
                          kind=g.kind)
            while g.requests and (taken_bytes < budget
                                  or not part.requests):
                regions, fut = g.requests.pop(0)
                n = self._req_bytes(g.kind, g.mbits, regions)
                part.requests.append((regions, fut))
                part.pending_bytes += n
                g.pending_bytes -= n
                taken_bytes += n
            if part.requests:
                taken.append(part)
            del self._groups[key]
            if g.requests:
                self._groups[key] = g  # re-insert at tail
            break
        self._pending = sum(g.pending_bytes
                            for g in self._groups.values())
        if self._pending <= 0:
            self._oldest = None
        # else: keep _oldest — the remainder is at least as old as the
        # round just taken, so its window is already (nearly) expired and
        # the next loop iteration dispatches it immediately (pipelining)
        return taken

    def _run(self) -> None:
        # double-buffered pipeline (VERDICT r03 #4): each round's batches
        # are STAGED to the device and their computations launched (JAX
        # dispatch is async — device_put and jitted calls return before
        # the work finishes) WITHOUT blocking; the previous round's
        # results are then fetched while round N's H2D transfer and
        # compute proceed underneath.  A launched round is held in-flight
        # only while more work is already queued, so an isolated batch
        # still completes immediately.
        inflight: Optional[list] = None
        while True:
            with self._cv:
                while not self._stop:
                    if self._pending >= self.max_pending_bytes:
                        break
                    if self._oldest is not None:
                        # pending work fills its normal coalescing window
                        # even while a round is in flight — that round's
                        # compute is proceeding on-device regardless, and
                        # an eager take here would fragment batches
                        remaining = self.max_delay - (time.monotonic() - self._oldest)
                        if remaining <= 0:
                            break
                        self._cv.wait(timeout=remaining)
                    elif inflight is not None:
                        break  # nothing queued: fetch the in-flight round
                    else:
                        self._cv.wait()
                if self._stop:
                    if inflight is not None:
                        self._complete_safe(inflight)
                    return
                groups = self._take_locked(budget=self.max_pending_bytes)
            launched = self._launch_safe(groups)
            if inflight is not None:
                if launched:
                    self.overlapped_rounds += 1
                self._complete_safe(inflight)
                inflight = None
            with self._cv:
                more = self._pending > 0 and not self._stop
            if launched and more:
                inflight = launched  # overlap with the next round
            elif launched:
                self._complete_safe(launched)

    def _launch_safe(self, groups: List[_Group]) -> list:
        launched = []
        for g in groups:
            if not g.requests:
                continue
            try:
                if g.kind == "planar":
                    state = self._launch_planar(g)
                elif g.kind == "resident":
                    state = self._launch_resident(g)
                else:
                    state = self._launch_packed(g)
                launched.append((g, state))
            except Exception as e:
                self._fail_group(g, e)
        if launched and self._launch_hook is not None:
            self._launch_hook()
        return launched

    def _complete_safe(self, launched: list) -> None:
        for g, state in launched:
            try:
                if g.kind == "planar":
                    self._complete_planar(g, state)
                elif g.kind == "resident":
                    self._complete_resident(g, state)
                else:
                    self._complete_packed(g, state)
            except Exception as e:
                self._fail_group(g, e)

    @staticmethod
    def _fail_group(g: _Group, e: Exception) -> None:
        for _, fut in g.requests:
            try:
                fut.set_exception(e)
            except InvalidStateError:
                pass

    def _dispatch(self, groups: List[_Group]) -> None:
        # synchronous drain (flush()/close()): launch then complete
        self._complete_safe(self._launch_safe(groups))


    def _maybe_shard(self, batch, pad_np: bool):
        """Lay a dispatch batch across the mesh when one is attached.
        Columns pad out to a device-grid multiple (bucket_columns gives
        powers of two, which a 6-device grid would never divide) — the
        pad is zeros beyond every request's slice, so fan-out offsets
        are unaffected.  Returns (batch, sharded)."""
        if self.mesh is None:
            return batch, False
        try:
            want = self.mesh.pad_cols(batch.shape[1])
            if want != batch.shape[1]:
                extra = want - batch.shape[1]
                if pad_np:
                    batch = np.pad(batch, ((0, 0), (0, extra)))
                else:
                    import jax.numpy as jnp

                    batch = jnp.pad(batch, ((0, 0), (0, extra)))
            return self.mesh.shard_batch(batch), True
        except Exception:
            return batch, False  # sick mesh: single-device still serves

    def _launch_packed(self, g: _Group):
        import jax

        from ceph_tpu.ops.gf2 import bucket_columns as _bucket
        from ceph_tpu.ops.gf2 import gf2_apply_bytes

        widths = [r.shape[1] for r, _ in g.requests]
        batch = np.concatenate([r for r, _ in g.requests], axis=1)
        pad = _bucket(batch.shape[1]) - batch.shape[1]
        if pad:
            batch = np.pad(batch, ((0, 0), (0, pad)))
        nbytes = batch.nbytes
        batch, sharded = self._maybe_shard(batch, pad_np=True)
        if not sharded:
            # explicit async staging: the H2D transfer starts NOW and
            # overlaps the previous round's result fetch
            batch = jax.device_put(batch)
        use_pallas = self._use_pallas and not sharded
        if use_pallas is None:
            from ceph_tpu.ops.gf2 import pallas_enabled
            from ceph_tpu.ops.pallas_gf2 import TILE_B
            from ceph_tpu.utils.jaxdev import probe_backend

            # pallas_call does not run under GSPMD sharding (it would
            # need a shard_map wrapper); sharded batches take XLA
            use_pallas = (
                not sharded
                and pallas_enabled()
                and probe_backend() == "tpu"
                and batch.shape[1] % TILE_B == 0
            )
        # async launch: the jitted call returns a device handle
        out = gf2_apply_bytes(g.mbits, batch, g.w, g.out_rows,
                              use_pallas=use_pallas)
        return widths, out, sharded, nbytes

    def _complete_packed(self, g: _Group, state) -> None:
        widths, out, sharded, nbytes = state
        out = np.asarray(out)  # blocks until compute + D2H done
        self.dispatches += 1
        self.sharded_dispatches += 1 if sharded else 0
        self.bytes_dispatched += nbytes
        off = 0
        for width, (_, fut) in zip(widths, g.requests):
            # a submitter may have been CANCELLED while waiting (an
            # async op torn down mid-flight propagates cancellation
            # into the future via asyncio.wrap_future): its slice is
            # simply dropped
            try:
                # copy: a view would pin the whole batch buffer for as
                # long as any single result stays alive
                fut.set_result(out[:, off : off + width].copy())
            except InvalidStateError:
                pass  # cancelled in the check-to-set window
            off += width

    def _launch_planar(self, g: _Group):
        """Matmul-only dispatch over HBM-resident bit-planes: ONE batched
        device call per (matrix) group; results are handed back as planar
        device buffers so the next stage chains without a host bounce."""
        import jax.numpy as jnp

        from ceph_tpu.ops.gf2 import bucket_columns as _bucket
        from ceph_tpu.ops.gf2 import gf2_matmul

        widths = [b.shape[1] for b, _ in g.requests]
        batch = (g.requests[0][0] if len(g.requests) == 1
                 else jnp.concatenate([b for b, _ in g.requests], axis=1))
        # pow2 column bucketing, same as the other lanes: varying
        # coalesced widths must not each compile a fresh gf2_matmul
        pad = _bucket(batch.shape[1]) - batch.shape[1]
        if pad:
            batch = jnp.pad(batch, ((0, 0), (0, pad)))
        batch, sharded = self._maybe_shard(batch, pad_np=False)
        out = gf2_matmul(jnp.asarray(g.mbits), batch)
        return widths, out, sharded

    def _complete_planar(self, g: _Group, state) -> None:
        widths, out, sharded = state
        self.dispatches += 1
        self.sharded_dispatches += 1 if sharded else 0
        self.bytes_dispatched += sum(w for w in widths) * g.mbits.shape[1] // 8
        off = 0
        for width, (_, fut) in zip(widths, g.requests):
            try:
                # device-side slice: stays planar-resident; no host copy
                fut.set_result(out[:, off : off + width])
            except InvalidStateError:
                pass
            off += width

    def _launch_resident(self, g: _Group):
        """Residency write path: ONE fused batched call — unpack the
        concatenated packed rows, matmul, pack the parity — and fan both
        products out per request: (packed parity for persistence, planar
        rows to stay HBM-resident)."""
        import jax

        from ceph_tpu.ops.gf2 import bucket_columns as _bucket
        from ceph_tpu.ops.gf2 import gf2_encode_resident

        widths = [r.shape[1] for r, _ in g.requests]
        batch = np.concatenate([r for r, _ in g.requests], axis=1)
        pad = _bucket(batch.shape[1]) - batch.shape[1]
        if pad:
            batch = np.pad(batch, ((0, 0), (0, pad)))
        nbytes = batch.nbytes
        batch, sharded = self._maybe_shard(batch, pad_np=True)
        # AFTER any mesh grid-padding: the planar fan-out factor must
        # relate all_bits' columns to the columns the matmul actually saw
        cols = batch.shape[1]
        if not sharded:
            batch = jax.device_put(batch)  # async H2D staging
        packed, all_bits = gf2_encode_resident(
            g.mbits, batch, g.w, g.out_rows)
        return widths, packed, all_bits, sharded, nbytes, cols

    def _complete_resident(self, g: _Group, state) -> None:
        widths, packed, all_bits, sharded, nbytes, cols = state
        packed = np.asarray(packed)  # blocks until ready
        self.dispatches += 1
        self.sharded_dispatches += 1 if sharded else 0
        self.bytes_dispatched += nbytes
        # planar columns per packed byte-column depends on w (w=16: B//2)
        cfac = all_bits.shape[1] / cols
        off = 0
        for width, (_, fut) in zip(widths, g.requests):
            try:
                c0, c1 = int(off * cfac), int((off + width) * cfac)
                fut.set_result((packed[:, off : off + width].copy(),
                                all_bits[:, c0:c1]))
            except InvalidStateError:
                pass
            off += width


class PlanarShardStore:
    """HBM-resident planar shard cache — the residency manager behind the
    measured ~1.6x pack-elimination win (ceph_tpu/ops/gf2.py writeup).

    Rows of packed uint8 shard bytes are admitted ONCE (one on-device
    unpack) and then live in HBM as int8 bit-planes; every subsequent EC
    op on them — encode, decode-reconstruct, scrub re-encode, recovery —
    is a pure GF(2) matmul chaining planar buffers, and bytes are packed
    back exactly once, when they leave for the wire/store.  The
    reference's analog is the stripe buffer staying cache-resident across
    ECUtil::encode's loop (reference src/osd/ECUtil.cc:123-160); here the
    residency scope is HBM across whole pipeline stages.

    Capacity is a hard byte budget over the PLANAR footprint (w x the
    packed bytes): least-recently-used entries are evicted, so the store
    degrades to the packed path, never to an OOM.  Thread-safe — the OSD
    event loop, the batching worker, and tests may touch it concurrently.
    """

    def __init__(self, capacity_bytes: int = 256 << 20,
                 queue: Optional[BatchingQueue] = None):
        from ceph_tpu.common.lockdep import make_mutex

        self.capacity_bytes = capacity_bytes
        self.queue = queue
        self._lock = make_mutex("planar-store")
        self._entries: "OrderedDict[Any, Any]" = OrderedDict()
        self._bytes: Dict[Any, int] = {}
        self.resident_bytes = 0
        self.admits = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- host boundary (pack/unpack paid here, once) -------------------------

    def admit(self, key: Any, rows: np.ndarray, w: int = 8,
              meta: Any = None):
        """Unpack packed [n, B] uint8 rows onto the device and keep them
        planar under `key`.  Returns the planar device buffer."""
        from ceph_tpu.ops.gf2 import to_planar

        bits = to_planar(np.ascontiguousarray(rows), w)
        self.put_planar(key, bits, w=w, n_rows=rows.shape[0], meta=meta)
        self.admits += 1
        return bits

    def read(self, key: Any) -> Optional[np.ndarray]:
        """Pack the resident planar rows back to [n, B] uint8 host bytes —
        the EXIT boundary.  None when not resident."""
        from ceph_tpu.ops.gf2 import from_planar

        got = self.get_planar(key)
        if got is None:
            return None
        bits, w, n_rows, _meta = got
        return np.asarray(from_planar(bits, w, n_rows))

    # -- resident side (no pack/unpack anywhere below) -----------------------

    def put_planar(self, key: Any, bits, w: int = 8,
                   n_rows: Optional[int] = None, meta: Any = None) -> None:
        """`meta` is caller state carried with the entry (the OSD stores
        the object VERSION there, so a read can reject a stale resident)."""
        if n_rows is None:
            n_rows = bits.shape[0] // w
        nbytes = int(np.prod(bits.shape))  # int8 planes: 1 byte/element
        with self._lock:
            if key in self._entries:
                self.resident_bytes -= self._bytes[key]
            self._entries[key] = (bits, w, n_rows, meta)
            self._entries.move_to_end(key)
            self._bytes[key] = nbytes
            self.resident_bytes += nbytes
            while self.resident_bytes > self.capacity_bytes and self._entries:
                old_key, _ = self._entries.popitem(last=False)
                self.resident_bytes -= self._bytes.pop(old_key)
                self.evictions += 1

    def get_planar(self, key: Any):
        """(bits, w, n_rows, meta) or None; refreshes LRU position."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent

    def apply(self, key: Any, mbits: np.ndarray, out_rows: int,
              out_key: Any = None):
        """Apply a bit-matrix to the resident planar rows (encode with a
        generator, reconstruct with an inverted signature matrix, scrub
        re-encode, ...).  Pure matmul; the result stays planar, stored
        under `out_key` when given.  Returns the planar device buffer, or
        None when `key` is not resident.  Routes through the batching
        queue when one is attached (cross-object coalescing)."""
        got = self.get_planar(key)
        if got is None:
            return None
        bits, w, _, _meta = got
        if self.queue is not None:
            out = self.queue.submit_planar(
                np.asarray(mbits), bits, w, out_rows).result()
        else:
            import jax.numpy as jnp

            from ceph_tpu.ops.gf2 import gf2_matmul

            out = gf2_matmul(jnp.asarray(np.asarray(mbits)), bits)
        if out_key is not None:
            self.put_planar(out_key, out, w=w, n_rows=out_rows)
        return out

    def drop(self, key: Any) -> None:
        with self._lock:
            if key in self._entries:
                del self._entries[key]
                self.resident_bytes -= self._bytes.pop(key)

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def stats(self) -> Dict[str, int]:
        return {"resident_bytes": self.resident_bytes,
                "entries": len(self._entries), "admits": self.admits,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}
