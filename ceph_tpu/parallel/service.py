"""Stripe-batching dispatch queue — amortizing many small EC ops into one
device call.

The reference dispatches its codec once per 4 KiB-unit stripe inside
ECUtil::encode (reference src/osd/ECUtil.cc:123-160) and per 1 MiB buffer in
the benchmark; a TPU dispatch has fixed launch latency, so the >=10x target
"lives or dies on the batching queue" (SURVEY.md §7 hard part 2).  This
queue aggregates encode/decode requests from many objects/ops, concatenates
them column-wise into one [rows, sum(B)] buffer per (matrix, layout) group,
runs ONE bit-plane matmul, and fans completions back out — the same
submit -> aggregate -> dispatch -> completion-fan-out pipeline ECBackend's
write path drives (submit_transaction -> ... -> try_reads_to_commit,
ECBackend.cc:1525->1989).

Threading model: submit() is non-blocking and returns a Future; a worker
thread flushes when pending bytes cross `max_pending_bytes` or `max_delay`
elapses, whichever first.  flush() forces a synchronous drain (used by
tests and by the benchmark's timed sections).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass
class _Group:
    mbits: np.ndarray
    w: int
    out_rows: int
    requests: List[Tuple[np.ndarray, Future]] = field(default_factory=list)
    pending_bytes: int = 0


class BatchingQueue:
    def __init__(
        self,
        max_pending_bytes: int = 64 << 20,
        max_delay: float = 0.002,
        use_pallas: Optional[bool] = None,
    ):
        self.max_pending_bytes = max_pending_bytes
        self.max_delay = max_delay
        self._use_pallas = use_pallas
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._groups: Dict[Tuple, _Group] = {}
        self._pending = 0
        self._oldest: Optional[float] = None
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True, name="ec-batch")
        self._worker.start()
        self.dispatches = 0  # perf counter: device calls issued
        self.bytes_dispatched = 0
        self.submits = 0  # requests accepted (ops/dispatch = submits/dispatches)

    # -- client side ---------------------------------------------------------

    def submit(
        self, mbits: np.ndarray, regions: np.ndarray, w: int, out_rows: int
    ) -> "Future[np.ndarray]":
        """Queue (mbits @ regions) over the byte layout; resolves to the
        [out_rows, B] parity/reconstruction buffer."""
        fut: Future = Future()
        # the full dispatch signature: identical matrix BYTES under a
        # different w or output arity is a different computation
        key = (w, out_rows, mbits.shape, mbits.tobytes())
        with self._cv:
            if self._stop:
                raise RuntimeError("BatchingQueue is closed")
            group = self._groups.get(key)
            if group is None:
                group = self._groups[key] = _Group(mbits=mbits, w=w, out_rows=out_rows)
            group.requests.append((regions, fut))
            self.submits += 1
            nbytes = regions.nbytes
            group.pending_bytes += nbytes
            self._pending += nbytes
            if self._oldest is None:
                self._oldest = time.monotonic()
            self._cv.notify()
        return fut

    def flush(self) -> None:
        """Synchronously drain everything queued right now."""
        with self._cv:
            groups = self._take_locked()
        self._dispatch(groups)

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._worker.join(timeout=5)
        self.flush()

    # -- worker side ---------------------------------------------------------

    def _take_locked(self) -> List[_Group]:
        groups = [g for g in self._groups.values() if g.requests]
        self._groups = {}
        self._pending = 0
        self._oldest = None
        return groups

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop:
                    if self._pending >= self.max_pending_bytes:
                        break
                    if self._oldest is not None:
                        remaining = self.max_delay - (time.monotonic() - self._oldest)
                        if remaining <= 0:
                            break
                        self._cv.wait(timeout=remaining)
                    else:
                        self._cv.wait()
                if self._stop:
                    return
                groups = self._take_locked()
            try:
                self._dispatch(groups)
            except Exception as e:
                # the worker must NEVER die: a process-shared queue with a
                # dead worker hangs every later submit.  _dispatch fans
                # per-group errors out; anything that escapes is a bug in
                # the fan-out itself — fail the taken groups' futures
                # (they were already removed from _groups, so nobody else
                # will resolve them) and keep serving.
                import traceback

                traceback.print_exc()
                for g in groups:
                    for _, fut in g.requests:
                        try:
                            fut.set_exception(e)
                        except InvalidStateError:
                            pass

    def _dispatch(self, groups: List[_Group]) -> None:
        from ceph_tpu.ops.gf2 import bucket_columns as _bucket
        from ceph_tpu.ops.gf2 import gf2_apply_bytes

        for g in groups:
            if not g.requests:
                continue
            widths = [r.shape[1] for r, _ in g.requests]
            batch = np.concatenate([r for r, _ in g.requests], axis=1)
            pad = _bucket(batch.shape[1]) - batch.shape[1]
            if pad:
                batch = np.pad(batch, ((0, 0), (0, pad)))
            use_pallas = self._use_pallas
            if use_pallas is None:
                from ceph_tpu.ops.gf2 import pallas_enabled
                from ceph_tpu.ops.pallas_gf2 import TILE_B
                from ceph_tpu.utils.jaxdev import probe_backend

                use_pallas = (
                    pallas_enabled()
                    and probe_backend() == "tpu"
                    and batch.shape[1] % TILE_B == 0
                )
            try:
                out = np.asarray(
                    gf2_apply_bytes(g.mbits, batch, g.w, g.out_rows, use_pallas=use_pallas)
                )
            except Exception as e:
                for _, fut in g.requests:
                    try:
                        fut.set_exception(e)
                    except InvalidStateError:
                        pass
                continue
            self.dispatches += 1
            self.bytes_dispatched += batch.nbytes
            off = 0
            for width, (_, fut) in zip(widths, g.requests):
                # a submitter may have been CANCELLED while waiting (an
                # async op torn down mid-flight propagates cancellation
                # into the future via asyncio.wrap_future): its slice is
                # simply dropped
                try:
                    # copy: a view would pin the whole batch buffer for as
                    # long as any single result stays alive
                    fut.set_result(out[:, off : off + width].copy())
                except InvalidStateError:
                    pass  # cancelled in the check-to-set window
                off += width
