"""Device-mesh execution for the EC service — multi-chip as a framework
capability, not a demo (VERDICT r03 #2).

The reference scales its compute tier across daemons with the messenger
(reference src/msg/async/AsyncMessenger.h:73) and OSD op shards
(src/osd/OSD.h:1590); the TPU-native equivalent is a
``jax.sharding.Mesh`` over the chips of a slice, with XLA inserting any
collectives over ICI (SURVEY.md §2.4 axis 6, §5.8).  The EC workload's
two decomposition axes (SURVEY.md §2.4):

  * ``stripe`` — data parallelism over independent stripes (axis 1,
    the per-stripe encode loop of reference src/osd/ECUtil.cc:123-160);
  * ``col``   — sub-chunk parallelism across the byte columns of a
    stripe (axis 3, the CLAY sub-chunk axis).

The BatchingQueue flattens stripes into the column axis of one
``[rows, sum(B)]`` batch, so sharding that column axis over BOTH mesh
axes shards every stripe and sub-chunk across every device: the GF(2)
matmul contracts over ROWS (the bit-planes), which are replicated, so
the dispatch is embarrassingly parallel — zero collectives on the hot
path, by construction.  Cross-device reduction only appears when a
consumer folds across columns (e.g. scrub checksums), and XLA inserts
the psum from the shardings.

Multi-host: under ``jax.distributed`` the same Mesh spans hosts (ICI
within a slice, DCN between), with no change here — the mesh is built
from ``jax.devices()``, whatever they are.

Engagement: ``shared_mesh()`` builds the dispatcher when the default
backend exposes >1 accelerator device, or when ``CEPH_TPU_MESH=1``
forces it (CPU-mesh tests and the driver's dryrun use the forced path
on the virtual 8-device CPU backend).  Single-device processes pay
nothing — the queue bypasses the mesh entirely.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

import numpy as np

_MESH_LOCK = threading.Lock()
_SHARED: Optional["MeshDispatcher"] = None
_SHARED_FAILED = False


def _factor_axes(n: int) -> Tuple[int, int]:
    """n devices -> (stripe, col) axis sizes, e.g. 8 -> (4, 2)."""
    col = 1
    for cand in (2, 4):
        if n % cand == 0:
            col = cand
    return n // col, col


class MeshDispatcher:
    """A (stripe, col) ``jax.sharding.Mesh`` plus the one operation the
    batching queue needs: lay a batch's column axis out across every
    device.  Holding the mesh (rather than building shardings inline)
    keeps one process-wide device layout, so residents produced by
    sharded dispatches and consumed by later ones never reshard."""

    def __init__(self, devices: Optional[list] = None):
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = list(jax.devices())
        if len(devices) < 2:
            raise ValueError("a mesh needs >= 2 devices")
        stripe, col = _factor_axes(len(devices))
        self.n_devices = len(devices)
        self.mesh = Mesh(
            np.asarray(devices).reshape(stripe, col), ("stripe", "col"))
        self.shard_puts = 0  # batches laid out across the mesh

    def column_sharding(self, ndim: int = 2):
        """NamedSharding splitting the LAST axis over every device and
        replicating the rest ([rows, cols] batches, [S, rows, cols]
        stripe-major arrays alike)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = [None] * (ndim - 1) + [("stripe", "col")]
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def pad_cols(self, n_cols: int) -> int:
        """Columns must split evenly across the device grid."""
        n = self.n_devices
        return -(-n_cols // n) * n

    def shard_batch(self, batch):
        """Lay [.., cols] out across the mesh (device_put; a no-op for
        arrays already in this layout).  The jitted EC ops pick the
        sharding up from the operand — jit caches one executable per
        sharding, so steady state compiles once."""
        import jax

        self.shard_puts += 1
        return jax.device_put(batch, self.column_sharding(batch.ndim))


def shared_mesh() -> Optional[MeshDispatcher]:
    """The process mesh, or None when multi-device execution should not
    engage (single device, CPU backend without the forced flag, or mesh
    construction failed once — a sick backend must not re-probe on every
    dispatch)."""
    global _SHARED, _SHARED_FAILED
    if _SHARED is not None:
        return _SHARED
    if _SHARED_FAILED:
        return None
    forced = os.environ.get("CEPH_TPU_MESH") == "1"
    if not forced:
        # an EXPLICIT JAX_PLATFORMS=cpu is an operator decision and wins
        # outright — on some hosts a sitecustomize-registered accelerator
        # plugin overrides the platform selection, so the backend probe
        # would still report the accelerator and silently route every
        # dispatch through it (same env-var-first discipline as
        # osd.shared_batching_queue)
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            return None
        from ceph_tpu.utils.jaxdev import probe_backend

        if probe_backend() != "tpu":
            return None
    with _MESH_LOCK:
        if _SHARED is not None or _SHARED_FAILED:
            return _SHARED
        try:
            import jax

            pool = list(jax.devices())
            if len(pool) < 2 and forced:
                # forced mode on a single-accelerator host: the virtual
                # CPU mesh (xla_force_host_platform_device_count) is the
                # multi-device pool — same preference the driver's
                # dryrun_multichip applies
                try:
                    pool = list(jax.devices("cpu"))
                except RuntimeError:
                    pass
            if len(pool) < 2:
                _SHARED_FAILED = True
                return None
            _SHARED = MeshDispatcher(pool)
        except Exception:
            _SHARED_FAILED = True
            return None
        return _SHARED
