"""Service layers over RADOS (reference src/librbd/, src/cls/, src/rgw/,
src/mds/): block images, in-OSD object classes, object gateway, and a
file namespace — each a thin, idiomatic consumer of the librados facade."""
