"""RBD export / import / diff streams (reference `rbd export`,
`rbd export-diff` / `rbd import-diff`, src/tools/rbd + librbd/api/DiffIterate).

A stream is a framed record sequence:

    magic  b"ceph_tpu-rbd-diff-v1\\n"
    b"m" + u32 len + JSON   stream metadata {size, from_snap, to_snap}
    b"w" + u64 off + u32 len + bytes   write these bytes at off
    b"z" + u64 off + u32 len           zero (trim) this extent
    b"e"                               end

A full export is a diff against the empty image (from_snap=None): only
allocated blocks are emitted, so sparse images stay sparse through a
backup round-trip.  Diffs enumerate blocks through the image OBJECT
MAPS (the fast-diff role): candidate set = union of both sides'
allocated blocks; bytes are compared so an allocated-but-identical
block is not shipped.  Blocks allocated in `from` but gone in `to`
become trim records, so a shrunken/discarded extent propagates.
"""

from __future__ import annotations

import json
import struct
from typing import BinaryIO, Optional

from ceph_tpu.services.rbd import RBD, Image, RbdError

MAGIC = b"ceph_tpu-rbd-diff-v1\n"
_W = struct.Struct("<QI")  # offset, length


def _emit_meta(out: BinaryIO, meta: dict) -> None:
    blob = json.dumps(meta).encode()
    out.write(b"m" + struct.pack("<I", len(blob)) + blob)


async def _side_state(img: Image, snap: Optional[str]):
    """(block set, size, reader) for one side of the diff."""
    if snap is None:
        return (set(img._hdr["object_map"]), img.size,
                lambda off, n: img.read(off, n))
    info = img._snaps().get(snap)
    if info is None:
        raise RbdError(f"no snapshot {snap!r}")
    return (set(info.get("object_map", ())), info["size"],
            lambda off, n: img.read_snap(snap, off, n))


async def export_diff(img: Image, out: BinaryIO,
                      from_snap: Optional[str] = None,
                      to_snap: Optional[str] = None) -> dict:
    """Write the delta from `from_snap` (None = empty image: a FULL
    export) up to `to_snap` (None = head).  Returns stream stats."""
    await img._refresh()
    if from_snap is None:
        from_blocks, from_size = set(), 0
        from_read = None
    else:
        from_blocks, from_size, from_read = await _side_state(
            img, from_snap)
    to_blocks, to_size, to_read = await _side_state(img, to_snap)
    out.write(MAGIC)
    _emit_meta(out, {"size": to_size, "from_snap": from_snap,
                     "to_snap": to_snap})
    bs = img.object_size
    written = trimmed = 0
    for idx in sorted(to_blocks | from_blocks):
        off = idx * bs
        if off >= to_size:
            continue  # beyond the target size: the size shrink trims it
        n = min(bs, to_size - off)
        if idx not in to_blocks:
            # allocated before, gone now: propagate the hole
            out.write(b"z" + _W.pack(off, n))
            trimmed += 1
            continue
        data = await to_read(off, n)
        if idx in from_blocks and from_read is not None \
                and off + n <= from_size:
            old = await from_read(off, n)
            if old == data:
                continue  # allocated both sides, identical: skip
        if not data.strip(b"\x00"):
            # all zeros: a trim record keeps the destination sparse
            out.write(b"z" + _W.pack(off, n))
            trimmed += 1
            continue
        out.write(b"w" + _W.pack(off, n) + data)
        written += 1
    out.write(b"e")
    return {"size": to_size, "blocks_written": written,
            "blocks_trimmed": trimmed}


async def export_image(img: Image, out: BinaryIO,
                       snap: Optional[str] = None) -> dict:
    """Full (sparse-preserving) export of head or a snapshot."""
    return await export_diff(img, out, from_snap=None, to_snap=snap)


def _read_exact(inp: BinaryIO, n: int) -> bytes:
    buf = inp.read(n)
    if len(buf) != n:
        raise RbdError("truncated diff stream")
    return buf


async def apply_diff(img: Image, inp: BinaryIO) -> dict:
    """Apply a diff stream to an image (rbd import-diff role).  The
    image is resized to the stream's recorded size first, so size
    changes (grow AND shrink) propagate."""
    if _read_exact(inp, len(MAGIC)) != MAGIC:
        raise RbdError("bad magic: not a ceph_tpu rbd diff stream")
    meta: dict = {}
    applied = trims = 0
    while True:
        tag = _read_exact(inp, 1)
        if tag == b"e":
            break
        if tag == b"m":
            (n,) = struct.unpack("<I", _read_exact(inp, 4))
            meta = json.loads(_read_exact(inp, n))
            if img.size != int(meta["size"]):
                await img.resize(int(meta["size"]))
        elif tag == b"w":
            off, n = _W.unpack(_read_exact(inp, _W.size))
            await img.write(off, _read_exact(inp, n))
            applied += 1
        elif tag == b"z":
            off, n = _W.unpack(_read_exact(inp, _W.size))
            # a zero record must DEALLOCATE where it can, but only
            # blocks the extent FULLY covers — a partial-block zero
            # extent (legal in the framed format) must not discard
            # live bytes outside [off, off+n).  The extent is clamped
            # to the image size (export_diff emits tail trims with
            # n = size - off; a foreign over-long extent must not
            # abort mid-stream after earlier records applied).
            bs = img.object_size
            end = min(off + n, img.size)
            if bool(img._hdr.get("parent")) and end > off:
                # a CLONE's hole is parent data, not zeros (reads fall
                # through to the parent snapshot) — dropping blocks or
                # skipping unallocated ones would resurrect the
                # parent's bytes where the stream says zero.
                # Materialize zeros instead (copy-up keeps the rest of
                # each block intact); hole preservation is the
                # flat-image optimization only.  Block-sized steps
                # bound memory for huge extents.
                pos = off
                while pos < end:
                    step = min(end - pos, bs - pos % bs)
                    await img.write(pos, b"\x00" * step)
                    pos += step
                trims += 1
                continue
            drop = []
            partial = []
            for i in (range(off // bs, (end - 1) // bs + 1)
                      if end > off else ()):
                b_start = i * bs
                b_end = min((i + 1) * bs, img.size)
                if off <= b_start and end >= b_end:
                    # fully covered up to the image size: the tail
                    # block of a non-aligned image deallocates too
                    # (holes stay holes through a backup round-trip)
                    if i in img._hdr["object_map"]:
                        drop.append(i)
                elif i in img._hdr["object_map"]:
                    # allocated partial head/tail: explicit zeros over
                    # just the extent; an UNALLOCATED partial is
                    # already zeros — writing would materialize it
                    partial.append((max(off, b_start), min(end, b_end)))
            for i in drop:
                try:
                    await img.ioctx.remove(img._data_oid(i),
                                           snapc=img._image_snapc())
                except Exception:
                    pass
            if drop:
                img._hdr["object_map"] = sorted(
                    set(img._hdr["object_map"]) - set(drop))
                await img._save_header(drop_blocks=drop)
            for p_off, p_end in partial:
                await img.write(p_off, b"\x00" * (p_end - p_off))
            trims += 1
        else:
            raise RbdError(f"bad record tag {tag!r}")
    return {"meta": meta, "writes": applied, "trims": trims}


async def import_image(rbd: RBD, name: str, inp: BinaryIO,
                       order: int = 22) -> Image:
    """Create `name` from a full export stream (rbd import role)."""
    head = _read_exact(inp, len(MAGIC))
    if head != MAGIC:
        raise RbdError("bad magic: not a ceph_tpu rbd diff stream")
    tag = _read_exact(inp, 1)
    if tag != b"m":
        raise RbdError("stream missing metadata record")
    (n,) = struct.unpack("<I", _read_exact(inp, 4))
    meta = json.loads(_read_exact(inp, n))
    img = await rbd.create(name, int(meta["size"]), order=order)
    while True:
        tag = _read_exact(inp, 1)
        if tag == b"e":
            break
        if tag == b"w":
            off, length = _W.unpack(_read_exact(inp, _W.size))
            await img.write(off, _read_exact(inp, length))
        elif tag == b"z":
            _W.unpack(_read_exact(inp, _W.size))  # fresh image: hole
        elif tag == b"m":
            (n,) = struct.unpack("<I", _read_exact(inp, 4))
            _read_exact(inp, n)
        else:
            raise RbdError(f"bad record tag {tag!r}")
    return img
