"""In-OSD object classes (reference src/cls/ + src/osd/ClassHandler.cc).

A class is a named bundle of methods executed ON the OSD against an
object: ``method(hctx, input) -> (retcode, output)`` where hctx exposes
read/write/xattr access to the target object.  The registry mirrors the
reference's dlopen ClassHandler: classes register at import; the OSD looks
them up at `op=call` dispatch.  EC pools return -EOPNOTSUPP exactly as the
reference does (doc/dev/osd_internals/erasure_coding/ecbackend.rst
"Object Classes") — class methods read/modify objects in place, which the
EC write path cannot do server-side.

Shipped classes mirror the most-used reference ones in miniature:
- ``lock``: advisory lock (cls_lock role) stored in an xattr
- ``refcount``: get/put a reference counter (cls_refcount role)
- ``version``: object version stamp (cls_version role)
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional, Tuple

ENOTSUP = -95
ENOENT = -2
EBUSY = -16


class ClsContext:
    """Handle the OSD passes to a class method (cls_method_context role)."""

    def __init__(self, data: Optional[bytes], xattrs: Dict[str, bytes]):
        self.data = data  # None: object absent
        self.xattrs = xattrs
        self.data_dirty = False
        self.xattrs_dirty = False

    def read(self) -> Optional[bytes]:
        return self.data

    def write(self, data: bytes) -> None:
        self.data = data
        self.data_dirty = True

    def getxattr(self, name: str) -> Optional[bytes]:
        return self.xattrs.get(name)

    def setxattr(self, name: str, value: bytes) -> None:
        self.xattrs[name] = value
        self.xattrs_dirty = True


Method = Callable[[ClsContext, bytes], Tuple[int, bytes]]


class ClassRegistry:
    def __init__(self):
        self._classes: Dict[str, Dict[str, Method]] = {}

    def register(self, cls_name: str, method: str, fn: Method) -> None:
        self._classes.setdefault(cls_name, {})[method] = fn

    def get(self, cls_name: str, method: str) -> Optional[Method]:
        return self._classes.get(cls_name, {}).get(method)

    def classes(self) -> Dict[str, list]:
        return {c: sorted(m) for c, m in self._classes.items()}


registry = ClassRegistry()


def cls_method(cls_name: str, method: str):
    def deco(fn: Method) -> Method:
        registry.register(cls_name, method, fn)
        return fn

    return deco


# -- shipped classes ---------------------------------------------------------


@cls_method("lock", "lock")
def _lock_acquire(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    req = json.loads(inp or b"{}")
    owner = req.get("owner", "")
    ttl = float(req.get("ttl", 30.0))
    raw = hctx.getxattr("lock.state")
    if raw:
        st = json.loads(raw)
        if (st.get("owner") and st["owner"] != owner
                and st.get("expires", 0) > time.time()):
            return EBUSY, json.dumps(st).encode()
    hctx.setxattr("lock.state", json.dumps(
        {"owner": owner, "expires": time.time() + ttl}).encode())
    return 0, b""


@cls_method("lock", "unlock")
def _lock_release(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    req = json.loads(inp or b"{}")
    raw = hctx.getxattr("lock.state")
    st = json.loads(raw) if raw else {}
    if not st.get("owner"):
        return ENOENT, b""
    if st["owner"] != req.get("owner", ""):
        return EBUSY, json.dumps(st).encode()
    hctx.setxattr("lock.state", b"{}")
    return 0, b""


@cls_method("lock", "info")
def _lock_info(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    return 0, hctx.getxattr("lock.state") or b"{}"


@cls_method("refcount", "get")
def _ref_get(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    n = int(hctx.getxattr("refcount") or b"0") + 1
    hctx.setxattr("refcount", str(n).encode())
    return 0, str(n).encode()


@cls_method("refcount", "put")
def _ref_put(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    n = max(0, int(hctx.getxattr("refcount") or b"0") - 1)
    hctx.setxattr("refcount", str(n).encode())
    return 0, str(n).encode()


@cls_method("version", "set")
def _ver_set(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    hctx.setxattr("cls.version", inp)
    return 0, b""


@cls_method("version", "get")
def _ver_get(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    v = hctx.getxattr("cls.version")
    if v is None:
        return ENOENT, b""
    return 0, v


EEXIST = -17
EINVAL = -22


def _json_or(default, raw):
    try:
        return json.loads(raw) if raw else default
    except (ValueError, TypeError):
        return default


# -- cls_rbd (reference src/cls/rbd/cls_rbd.cc) ------------------------------
#
# RBD header operations executed IN the OSD against the rbd_header object:
# each call is one atomic read-mutate-write under the PG's op
# serialization, so concurrent clients cannot lose header updates the way
# client-side read-modify-write races do (VERDICT r03 #5).  The header is
# the service's JSON record; methods mirror the reference's create /
# snapshot_add / snapshot_remove / set_protection_status /
# object_map_update family.


@cls_method("rbd", "create")
def _rbd_create(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    if hctx.read():
        return EEXIST, b""
    req = _json_or(None, inp)
    if not isinstance(req, dict) or "header" not in req:
        return EINVAL, b""
    hctx.write(json.dumps(req["header"]).encode())
    return 0, b""


@cls_method("rbd", "get")
def _rbd_get(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if not raw:
        return ENOENT, b""
    return 0, bytes(raw)


@cls_method("rbd", "snap_create")
def _rbd_snap_create(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if not raw:
        return ENOENT, b""
    hdr = _json_or(None, raw)
    req = _json_or({}, inp)
    name, snap_id = req.get("name"), req.get("snap_id")
    if hdr is None or not name or snap_id is None:
        return EINVAL, b""
    snaps = hdr.setdefault("snaps", {})
    if name in snaps:
        return EEXIST, b""
    snaps[name] = {"id": snap_id, "size": hdr["size"],
                   "object_map": list(hdr["object_map"])}
    hctx.write(json.dumps(hdr).encode())
    return 0, json.dumps(hdr).encode()


@cls_method("rbd", "snap_remove")
def _rbd_snap_remove(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if not raw:
        return ENOENT, b""
    hdr = _json_or(None, raw)
    req = _json_or({}, inp)
    name = req.get("name")
    if hdr is None or not name:
        return EINVAL, b""
    snap = hdr.get("snaps", {}).get(name)
    if snap is None:
        return ENOENT, b""
    if snap.get("protected"):
        return EBUSY, b""
    hdr["snaps"].pop(name)
    hctx.write(json.dumps(hdr).encode())
    return 0, json.dumps(hdr).encode()


@cls_method("rbd", "set_protection")
def _rbd_set_protection(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if not raw:
        return ENOENT, b""
    hdr = _json_or(None, raw)
    req = _json_or({}, inp)
    name = req.get("name")
    if hdr is None or not name:
        return EINVAL, b""
    snap = hdr.get("snaps", {}).get(name)
    if snap is None:
        return ENOENT, b""
    snap["protected"] = bool(req.get("protected"))
    hctx.write(json.dumps(hdr).encode())
    return 0, json.dumps(hdr).encode()


@cls_method("rbd", "merge_object_map")
def _rbd_merge_object_map(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    """Atomic object-map union (reference object_map_update role): the
    client-side read-modify-write of the whole header LOSES blocks when
    two writers race; this merge cannot."""
    raw = hctx.read()
    if not raw:
        return ENOENT, b""
    hdr = _json_or(None, raw)
    req = _json_or({}, inp)
    if hdr is None:
        return EINVAL, b""
    objmap = set(hdr.get("object_map", []))
    objmap.update(int(i) for i in req.get("add", ()))
    for i in req.get("remove", ()):
        objmap.discard(int(i))
    hdr["object_map"] = sorted(objmap)
    hctx.write(json.dumps(hdr).encode())
    return 0, json.dumps(hdr).encode()


@cls_method("rbd", "set_header")
def _rbd_set_header(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    """Header update for image ops (resize, parent link/unlink, journal
    fields).  NOT a blind replace: the stored object_map and snaps are
    MERGED into the provided header (minus an explicit drop_blocks
    list), so a client whose cached header predates a concurrent
    writer's merge_object_map or snap_create cannot silently discard
    those updates.  Returns the merged header for the caller to adopt."""
    req = _json_or(None, inp)
    if not isinstance(req, dict) or "header" not in req:
        return EINVAL, b""
    raw = hctx.read()
    if not raw:
        return ENOENT, b""
    stored = _json_or({}, raw)
    hdr = req["header"]
    om = set(stored.get("object_map", [])) | set(hdr.get("object_map", []))
    om -= {int(i) for i in req.get("drop_blocks", ())}
    hdr["object_map"] = sorted(om)
    # snaps present only in the store survive (snap removal goes through
    # snap_remove, never through a header push); for names in both, the
    # STORED entry wins (protection flips land via set_protection)
    merged_snaps = dict(hdr.get("snaps", {}))
    merged_snaps.update(stored.get("snaps", {}))
    if merged_snaps:
        hdr["snaps"] = merged_snaps
    blob = json.dumps(hdr).encode()
    hctx.write(blob)
    return 0, blob


# -- cls_rgw (reference src/cls/rgw/cls_rgw.cc) ------------------------------
#
# Bucket-index mutation executed IN the OSD against the index object: the
# reference's bucket index is a cls-maintained omap precisely so that
# concurrent gateways update it atomically; the client-side
# _load_index/_save_index read-modify-write this replaces loses entries
# under racing PUTs.


@cls_method("rgw", "bucket_init")
def _rgw_bucket_init(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    if hctx.read():
        return EEXIST, b""
    hctx.write(b"{}")
    return 0, b""


@cls_method("rgw", "index_put")
def _rgw_index_put(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if raw is None:
        return ENOENT, b""  # NoSuchBucket
    index = _json_or({}, raw)
    req = _json_or({}, inp)
    key = req.get("key")
    if not key:
        return EINVAL, b""
    prev = index.get(key)
    index[key] = req.get("meta", {})
    hctx.write(json.dumps(index).encode())
    return 0, json.dumps({"prev": prev}).encode()


@cls_method("rgw", "index_rm")
def _rgw_index_rm(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if raw is None:
        return ENOENT, b""
    index = _json_or({}, raw)
    req = _json_or({}, inp)
    key = req.get("key")
    if not key:
        return EINVAL, b""
    prev = index.pop(key, None)
    if prev is None:
        return ENOENT, b""
    hctx.write(json.dumps(index).encode())
    return 0, json.dumps({"prev": prev}).encode()


@cls_method("rgw", "index_set_tags")
def _rgw_index_set_tags(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    """Object tagging rides the bucket index entry (reference
    cls_rgw + rgw_tag.cc: tags live in the object's index/attrs, not
    the data): set, or clear with tags=None."""
    raw = hctx.read()
    if raw is None:
        return ENOENT, b""
    index = _json_or({}, raw)
    req = _json_or({}, inp)
    key = req.get("key")
    if not key or key not in index:
        return ENOENT, b""
    entry = index[key]
    tags = req.get("tags")
    if tags is None:
        entry.pop("tags", None)
    else:
        if not isinstance(tags, dict) or len(tags) > 10:
            return EINVAL, b""  # S3 caps object tag sets at 10
        entry["tags"] = tags
    hctx.write(json.dumps(index).encode())
    return 0, b""


@cls_method("rgw", "index_list")
def _rgw_index_list(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if raw is None:
        return ENOENT, b""
    index = _json_or({}, raw)
    req = _json_or({}, inp)
    after = req.get("after", "")
    limit = int(req.get("max", 0)) or len(index)
    keys = sorted(k for k in index if k > after)[:limit]
    return 0, json.dumps({k: index[k] for k in keys}).encode()


@cls_method("rgw", "registry_add")
def _rgw_registry_add(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    """Atomic bucket-registry append (the buckets root object)."""
    req = _json_or({}, inp)
    bucket = req.get("bucket")
    if not bucket:
        return EINVAL, b""
    buckets = _json_or([], hctx.read() or b"[]")
    if bucket not in buckets:
        buckets.append(bucket)
        hctx.write(json.dumps(sorted(buckets)).encode())
    return 0, b""


@cls_method("rgw", "registry_rm")
def _rgw_registry_rm(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    req = _json_or({}, inp)
    bucket = req.get("bucket")
    if not bucket:
        return EINVAL, b""
    buckets = _json_or([], hctx.read() or b"[]")
    if bucket in buckets:
        buckets.remove(bucket)
        hctx.write(json.dumps(buckets).encode())
    return 0, b""


@cls_method("rgw", "index_put_version")
def _rgw_index_put_version(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    """Append one object VERSION to an index entry atomically (reference
    cls_rgw versioned-bucket index ops): the entry keeps its full
    version stack plus derived newest-live size/etag for flat readers."""
    raw = hctx.read()
    if raw is None:
        return ENOENT, b""
    index = _json_or({}, raw)
    req = _json_or({}, inp)
    key, ver = req.get("key"), req.get("version")
    if not key or not isinstance(ver, dict):
        return EINVAL, b""
    entry = index.get(key)
    if not isinstance(entry, dict) or "versions" not in entry:
        entry = {"versions": ([] if entry is None else
                              [dict(entry, vid="null",
                                    ts=entry.get("ts", 0))])}
    entry["versions"].append(ver)
    cur = entry["versions"][-1]
    cur = None if cur.get("delete_marker") else cur
    entry["size"] = cur.get("size", 0) if cur else 0
    entry["etag"] = cur.get("etag", "") if cur else ""
    index[key] = entry
    hctx.write(json.dumps(index).encode())
    return 0, json.dumps(entry).encode()


@cls_method("rgw", "index_rm_version")
def _rgw_index_rm_version(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    raw = hctx.read()
    if raw is None:
        return ENOENT, b""
    index = _json_or({}, raw)
    req = _json_or({}, inp)
    key, vid = req.get("key"), req.get("vid")
    entry = index.get(key)
    if not key or not vid or not isinstance(entry, dict) \
            or "versions" not in entry:
        return ENOENT, b""
    removed = [v for v in entry["versions"] if v.get("vid") == vid]
    if not removed:
        return ENOENT, b""
    entry["versions"] = [v for v in entry["versions"]
                         if v.get("vid") != vid]
    if entry["versions"]:
        cur = entry["versions"][-1]
        cur = None if cur.get("delete_marker") else cur
        entry["size"] = cur.get("size", 0) if cur else 0
        entry["etag"] = cur.get("etag", "") if cur else ""
        index[key] = entry
    else:
        index.pop(key)
    hctx.write(json.dumps(index).encode())
    return 0, json.dumps({"removed": removed[0]}).encode()
