"""In-OSD object classes (reference src/cls/ + src/osd/ClassHandler.cc).

A class is a named bundle of methods executed ON the OSD against an
object: ``method(hctx, input) -> (retcode, output)`` where hctx exposes
read/write/xattr access to the target object.  The registry mirrors the
reference's dlopen ClassHandler: classes register at import; the OSD looks
them up at `op=call` dispatch.  EC pools return -EOPNOTSUPP exactly as the
reference does (doc/dev/osd_internals/erasure_coding/ecbackend.rst
"Object Classes") — class methods read/modify objects in place, which the
EC write path cannot do server-side.

Shipped classes mirror the most-used reference ones in miniature:
- ``lock``: advisory lock (cls_lock role) stored in an xattr
- ``refcount``: get/put a reference counter (cls_refcount role)
- ``version``: object version stamp (cls_version role)
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional, Tuple

ENOTSUP = -95
ENOENT = -2
EBUSY = -16


class ClsContext:
    """Handle the OSD passes to a class method (cls_method_context role)."""

    def __init__(self, data: Optional[bytes], xattrs: Dict[str, bytes]):
        self.data = data  # None: object absent
        self.xattrs = xattrs
        self.data_dirty = False
        self.xattrs_dirty = False

    def read(self) -> Optional[bytes]:
        return self.data

    def write(self, data: bytes) -> None:
        self.data = data
        self.data_dirty = True

    def getxattr(self, name: str) -> Optional[bytes]:
        return self.xattrs.get(name)

    def setxattr(self, name: str, value: bytes) -> None:
        self.xattrs[name] = value
        self.xattrs_dirty = True


Method = Callable[[ClsContext, bytes], Tuple[int, bytes]]


class ClassRegistry:
    def __init__(self):
        self._classes: Dict[str, Dict[str, Method]] = {}

    def register(self, cls_name: str, method: str, fn: Method) -> None:
        self._classes.setdefault(cls_name, {})[method] = fn

    def get(self, cls_name: str, method: str) -> Optional[Method]:
        return self._classes.get(cls_name, {}).get(method)

    def classes(self) -> Dict[str, list]:
        return {c: sorted(m) for c, m in self._classes.items()}


registry = ClassRegistry()


def cls_method(cls_name: str, method: str):
    def deco(fn: Method) -> Method:
        registry.register(cls_name, method, fn)
        return fn

    return deco


# -- shipped classes ---------------------------------------------------------


@cls_method("lock", "lock")
def _lock_acquire(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    req = json.loads(inp or b"{}")
    owner = req.get("owner", "")
    ttl = float(req.get("ttl", 30.0))
    raw = hctx.getxattr("lock.state")
    if raw:
        st = json.loads(raw)
        if (st.get("owner") and st["owner"] != owner
                and st.get("expires", 0) > time.time()):
            return EBUSY, json.dumps(st).encode()
    hctx.setxattr("lock.state", json.dumps(
        {"owner": owner, "expires": time.time() + ttl}).encode())
    return 0, b""


@cls_method("lock", "unlock")
def _lock_release(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    req = json.loads(inp or b"{}")
    raw = hctx.getxattr("lock.state")
    st = json.loads(raw) if raw else {}
    if not st.get("owner"):
        return ENOENT, b""
    if st["owner"] != req.get("owner", ""):
        return EBUSY, json.dumps(st).encode()
    hctx.setxattr("lock.state", b"{}")
    return 0, b""


@cls_method("lock", "info")
def _lock_info(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    return 0, hctx.getxattr("lock.state") or b"{}"


@cls_method("refcount", "get")
def _ref_get(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    n = int(hctx.getxattr("refcount") or b"0") + 1
    hctx.setxattr("refcount", str(n).encode())
    return 0, str(n).encode()


@cls_method("refcount", "put")
def _ref_put(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    n = max(0, int(hctx.getxattr("refcount") or b"0") - 1)
    hctx.setxattr("refcount", str(n).encode())
    return 0, str(n).encode()


@cls_method("version", "set")
def _ver_set(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    hctx.setxattr("cls.version", inp)
    return 0, b""


@cls_method("version", "get")
def _ver_get(hctx: ClsContext, inp: bytes) -> Tuple[int, bytes]:
    v = hctx.getxattr("cls.version")
    if v is None:
        return ENOENT, b""
    return 0, v
