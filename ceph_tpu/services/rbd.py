"""RBD-lite: block images striped over RADOS objects.

Role-equivalent of the reference's librbd core data path (reference
src/librbd/): an image is a header object (size, object order, id) plus
data objects ``rbd_data.<id>.<n>`` of 2^order bytes each; reads/writes map
byte extents onto data objects; unwritten extents read as zeros (sparse).
The object map (which blocks exist, reference object-map feature) lives in
the header and makes sparse reads and fast remove possible without listing.

Snapshots sit on RADOS self-managed snaps exactly as the reference's
librbd sits on librados (IoCtxImpl selfmanaged snap ops): snap_create
allocates a pool-unique snap id from the mon and records the object map;
every data write carries the image's SnapContext so the OSD primary
clones a block before its first post-snap write (make_writeable);
snapshot reads resolve per object through the RADOS SnapSet (covering
clone, unchanged head, or absent); snap removal trims clones that no
live snap still references.

Layered clones (reference librbd clone v2, src/librbd/ + cls_rbd
children bookkeeping): a PROTECTED snapshot can be cloned into a child
image whose header records the parent (image, snap).  Child reads fall
through to the parent snapshot for objects the child has never written;
child writes COPY-UP the parent block first when partially overwriting
(reference CopyupRequest), so the child diverges object by object.
``flatten`` copies every remaining parent block into the child and drops
the parent link; ``snap_unprotect`` refuses while children exist (tracked
in a pool-level ``rbd_children`` registry, the reference's cls_rbd
children object).

Journaling + mirroring (reference journal feature, src/journal/
Journaler.h, and the rbd-mirror daemon): a JournaledImage appends every
mutation to a per-image segmented journal BEFORE applying it, and a
Mirrorer replays those events into a peer pool's image resumably (the
replay position persists with the peer), expiring replayed segments.
"""

from __future__ import annotations

import asyncio
import errno
import json
import time
import uuid
from typing import Dict, List, Optional

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx

DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


class RbdError(Exception):
    pass


class Image:
    def __init__(self, ioctx: IoCtx, name: str, header: Dict):
        self.ioctx = ioctx
        self.name = name
        self._hdr = header

    # -- layout --------------------------------------------------------------

    @property
    def size(self) -> int:
        return self._hdr["size"]

    @property
    def object_size(self) -> int:
        return 1 << self._hdr["order"]

    def _data_oid(self, index: int) -> str:
        return f"rbd_data.{self._hdr['id']}.{index:016d}"

    @staticmethod
    def _header_oid(name: str) -> str:
        return f"rbd_header.{name}"

    async def _save_header(self, drop_blocks=()) -> None:
        """Whole-header write-back.  Routed through the in-OSD rbd class
        (reference cls_rbd, src/cls/rbd/cls_rbd.cc) when the pool
        supports class calls, so a concurrent merge_object_map cannot
        interleave mid-update; EC pools (class calls answer EOPNOTSUPP
        per reference semantics) keep the client-side write."""
        got = await self._hdr_cls(
            "set_header",
            {"header": self._hdr, "drop_blocks": sorted(drop_blocks)})
        if got is not None:
            ret, out = got
            if ret == 0:
                # adopt the server-side merge: concurrent writers'
                # object-map/snap updates survive our push
                self._hdr = json.loads(out)
                return
            if ret != -errno.ENOENT:
                raise RbdError(f"set_header failed ({ret})")
            # header object vanished (image being removed): fall through
        await self.ioctx.write_full(self._header_oid(self.name),
                                    json.dumps(self._hdr).encode())

    async def _hdr_cls(self, method: str, payload: Dict):
        """(ret, out) from an in-OSD rbd-class call on this image's
        header, or None on an EC pool (caller takes the client path)."""
        try:
            return await self.ioctx.execute(
                self._header_oid(self.name), "rbd", method,
                json.dumps(payload).encode())
        except RadosError as e:
            if e.code == -errno.EOPNOTSUPP:
                return None
            raise

    # -- IO ------------------------------------------------------------------

    async def _parent(self) -> Optional["Image"]:
        """Open the parent image of a clone.  NOT cached: the parent's
        header carries the snap COW bookkeeping, and a parent head write
        after we opened it would otherwise leak post-snap bytes into the
        child's read-through (the clone must always resolve through the
        parent's CURRENT clone map)."""
        p = self._hdr.get("parent")
        if not p:
            return None
        raw = await self.ioctx.read(self._header_oid(p["image"]))
        return Image(self.ioctx, p["image"], json.loads(raw))

    async def _read_from_parent(self, idx: int,
                                parent: Optional["Image"] = None) -> bytes:
        """A clone's view of one object it never wrote: the parent
        SNAPSHOT's bytes for that block (zeros past the snap's extent) —
        the read-fall-through half of the reference's clone layering.
        Callers doing many blocks pass ``parent`` (opened once per call)
        so each block does not re-read the parent header."""
        p = self._hdr.get("parent")
        parent = parent if parent is not None else await self._parent()
        if parent is None:
            return b""
        base = idx * self.object_size
        limit = min(p["size"], self.size)
        if base >= limit:
            return b""
        n = min(self.object_size, limit - base)
        return await parent.read_snap(p["snap"], base, n)

    async def read(self, offset: int, length: int) -> bytes:
        if offset >= self.size:
            return b""
        length = min(length, self.size - offset)
        objmap = set(self._hdr["object_map"])
        layered = bool(self._hdr.get("parent"))
        out = bytearray()
        pos = offset
        end = offset + length
        spans = []
        while pos < end:
            idx = pos // self.object_size
            off_in = pos % self.object_size
            n = min(self.object_size - off_in, end - pos)
            spans.append((idx, off_in, n))
            pos += n

        parent = await self._parent() if layered else None

        async def fetch(idx: int):
            if idx in objmap:
                return await self.ioctx.read(self._data_oid(idx))
            if layered:
                return await self._read_from_parent(idx, parent)
            return None

        datas = await asyncio.gather(*(fetch(idx) for idx, _, _ in spans))
        for (idx, off_in, n), blob in zip(spans, datas):
            if not blob:
                out.extend(b"\x00" * n)  # sparse hole
            else:
                piece = blob[off_in:off_in + n]
                out.extend(piece)
                out.extend(b"\x00" * (n - len(piece)))  # short object tail
        return bytes(out)

    async def write(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise RbdError("write beyond image size (resize first)")
        objmap = set(self._hdr["object_map"])
        layered = bool(self._hdr.get("parent"))
        snapc = self._image_snapc()  # every data write carries the context:
        # the OSD primary clones a block before its first post-snap write
        pos = 0
        dirty_map = False
        while pos < len(data):
            lofs = offset + pos
            idx = lofs // self.object_size
            off_in = lofs % self.object_size
            n = min(self.object_size - off_in, len(data) - pos)
            piece = data[pos:pos + n]
            if (layered and idx not in objmap
                    and (off_in or n < self.object_size)):
                # copy-up (reference CopyupRequest): a partial write to a
                # block the clone never owned must compose with the
                # PARENT's bytes, not zeros — materialize the parent block
                # in the child first, then overwrite part of it
                base = await self._read_from_parent(idx)
                if base:
                    await self.ioctx.write_full(self._data_oid(idx), base,
                                                snapc=snapc)
                    objmap.add(idx)
                    dirty_map = True
            if idx in objmap and (off_in or n < self.object_size):
                # partial overwrite rides the OSD's RMW path
                await self.ioctx.write(self._data_oid(idx), piece,
                                       offset=off_in, snapc=snapc)
            elif off_in or n < self.object_size:
                # sparse partial write into a fresh object: pad the head
                await self.ioctx.write_full(self._data_oid(idx),
                                            b"\x00" * off_in + piece,
                                            snapc=snapc)
            else:
                await self.ioctx.write_full(self._data_oid(idx), piece,
                                            snapc=snapc)
            if idx not in objmap:
                objmap.add(idx)
                dirty_map = True
            pos += n
        if dirty_map:
            await self._merge_object_map(objmap)

    async def _merge_object_map(self, objmap) -> None:
        """Record newly-materialized blocks.  In-OSD merge (cls_rbd
        object_map_update role): two clients writing disjoint blocks
        concurrently must both land — the client-side whole-header
        rewrite loses one side's blocks in that race."""
        got = await self._hdr_cls("merge_object_map",
                                  {"add": sorted(objmap)})
        if got is not None:
            ret, out = got
            if ret != 0:
                raise RbdError(f"object map update failed ({ret})")
            self._hdr = json.loads(out)
            return
        self._hdr["object_map"] = sorted(
            set(self._hdr["object_map"]) | set(objmap))
        await self._save_header()

    async def resize(self, new_size: int) -> None:
        old_size = self.size
        old_objects = (old_size + self.object_size - 1) // self.object_size
        new_objects = (new_size + self.object_size - 1) // self.object_size
        dropped = []
        if new_size < old_size:
            snapc = self._image_snapc()
            objmap = set(self._hdr["object_map"])
            for idx in range(new_objects, old_objects):
                if idx in objmap:
                    try:
                        # under a snap context the OSD clones first and
                        # whiteouts, so snapshots keep their blocks
                        await self.ioctx.remove(self._data_oid(idx),
                                                snapc=snapc)
                    except RadosError:
                        pass
                    objmap.discard(idx)
                    dropped.append(idx)
            # truncate the partial boundary object so a later grow reads
            # zeros, not pre-shrink data (reference librbd trims it)
            tail = new_size % self.object_size
            bidx = new_size // self.object_size
            if tail and bidx in objmap:
                try:
                    blob = await self.ioctx.read(self._data_oid(bidx))
                    await self.ioctx.write_full(self._data_oid(bidx),
                                                blob[:tail], snapc=snapc)
                except RadosError:
                    pass
            self._hdr["object_map"] = sorted(objmap)
        self._hdr["size"] = new_size
        await self._save_header(drop_blocks=dropped)

    async def stat(self) -> Dict:
        return {"size": self.size, "object_size": self.object_size,
                "num_objs": len(self._hdr["object_map"]),
                "snaps": sorted(self._hdr.get("snaps", {})),
                "id": self._hdr["id"]}

    # -- snapshots (RADOS self-managed snaps, librbd snapshot role) ----------
    # Rebased onto the RADOS-level primitive: writes carry the image's
    # snap context, the OSD primary does the per-object COW clone
    # (make_writeable), snap reads resolve through the object's SnapSet,
    # and snap removal trims clones that no live snap references — the
    # clone-sharing/re-homing bookkeeping the service layer used to
    # maintain is the storage layer's job now (reference librbd sits on
    # librados selfmanaged snaps the same way).

    def _snaps(self) -> Dict[str, Dict]:
        return self._hdr.setdefault("snaps", {})

    async def _refresh(self) -> None:
        """Re-read the header (reference ImageCtx refresh on header
        watch): another handle (a group snapshot sweep, a concurrent
        admin) may have changed snaps/map since this handle opened."""
        raw = await self.ioctx.read(self._header_oid(self.name))
        self._hdr = json.loads(raw)

    async def _snap_or_refresh(self, name: str) -> Optional[Dict]:
        """The snap record, refreshing ONCE when the local header does
        not know the name — absorbing out-of-band snap creation without
        a watch/notify channel.  Data WRITES still require the owning
        handle (the reference's exclusive-lock discipline)."""
        snap = self._snaps().get(name)
        if snap is None:
            await self._refresh()
            snap = self._snaps().get(name)
        return snap

    def _image_snapc(self):
        """(seq, snaps-descending) over the image's live snaps — the
        SnapContext every data-object write rides."""
        ids = sorted((s["id"] for s in self._snaps().values()),
                     reverse=True)
        if not ids:
            return None
        return (ids[0], ids)

    async def snap_create(self, name: str) -> None:
        """Single in-OSD call (cls_rbd snapshot_add role): the snap
        lands in the header atomically against concurrent writers'
        object-map merges."""
        if name in self._snaps():
            raise RbdError(f"snapshot {name!r} exists")
        snap_id = await self.ioctx.allocate_snap_id()
        got = await self._hdr_cls("snap_create",
                                  {"name": name, "snap_id": snap_id})
        if got is not None:
            ret, out = got
            if ret != 0:
                # ANY failure releases the freshly-allocated id — a
                # leaked id keeps its clones untrimmable forever
                await self.ioctx.release_snap_id(snap_id)
                if ret == -17:
                    raise RbdError(f"snapshot {name!r} exists")
                raise RbdError(f"snap_create failed ({ret})")
            self._hdr = json.loads(out)
            return
        snaps = self._snaps()
        snaps[name] = {"id": snap_id, "size": self.size,
                       "object_map": list(self._hdr["object_map"])}
        await self._save_header()

    def snap_list(self) -> List[str]:
        return sorted(self._snaps())

    async def read_snap(self, name: str, offset: int, length: int) -> bytes:
        """Read from a snapshot: each object resolves at the snap id
        through its RADOS SnapSet (covering clone, unchanged head, or
        absent)."""
        snap = await self._snap_or_refresh(name)
        if snap is None:
            raise RbdError(f"no snapshot {name!r}")
        size = snap["size"]
        if offset >= size:
            return b""
        length = min(length, size - offset)
        spans = []
        pos = offset
        end = offset + length
        while pos < end:
            idx = pos // self.object_size
            off_in = pos % self.object_size
            n = min(self.object_size - off_in, end - pos)
            spans.append((idx, off_in, n))
            pos += n

        layered = bool(self._hdr.get("parent"))
        parent = await self._parent() if layered else None

        async def resolve(idx: int):
            if idx not in snap["object_map"]:
                # a clone's snapshot: blocks it never wrote were (and
                # still are) served by ITS parent snapshot — fall through
                # so clones-of-clones don't read zeros for
                # grandparent-backed data
                if layered:
                    return await self._read_from_parent(idx, parent)
                return None
            try:
                return await self.ioctx.read(self._data_oid(idx),
                                             snap=snap["id"])
            except RadosError as e:
                if e.code != -errno.ENOENT:
                    raise
                return b""

        blobs = await asyncio.gather(*(resolve(idx) for idx, _, _ in spans))
        out = bytearray()
        for (idx, off_in, n), blob in zip(spans, blobs):
            if blob is None:
                out.extend(b"\x00" * n)
            else:
                piece = blob[off_in:off_in + n]
                out.extend(piece)
                out.extend(b"\x00" * (n - len(piece)))
        return bytes(out)

    async def snap_protect(self, name: str) -> None:
        """Mark a snapshot protected — the precondition for cloning
        (reference: clones may only be made from protected snaps, so a
        snap can never vanish under its children)."""
        snap = await self._snap_or_refresh(name)
        if snap is None:
            raise RbdError(f"no snapshot {name!r}")
        got = await self._hdr_cls("set_protection",
                                  {"name": name, "protected": True})
        if got is not None:
            ret, out = got
            if ret != 0:
                raise RbdError(f"snap_protect failed ({ret})")
            self._hdr = json.loads(out)
            return
        snap["protected"] = True
        await self._save_header()

    async def snap_unprotect(self, name: str) -> None:
        snap = self._snaps().get(name)
        if snap is None:
            raise RbdError(f"no snapshot {name!r}")
        children = await RBD(self.ioctx).children(self.name, name)
        if children:
            raise RbdError(
                f"snapshot {name!r} has children {children}; flatten or "
                f"remove them first")
        got = await self._hdr_cls("set_protection",
                                  {"name": name, "protected": False})
        if got is not None:
            ret, out = got
            if ret != 0:
                raise RbdError(f"snap_unprotect failed ({ret})")
            self._hdr = json.loads(out)
            return
        snap["protected"] = False
        await self._save_header()

    async def flatten(self) -> None:
        """Copy every block the clone still reads through its parent into
        the clone itself, then drop the parent link (reference
        librbd::flatten) — afterwards the parent snap can be unprotected
        and the parent removed."""
        p = self._hdr.get("parent")
        if not p:
            return
        objmap = set(self._hdr["object_map"])
        limit = min(p["size"], self.size)
        n_objs = (limit + self.object_size - 1) // self.object_size
        parent = await self._parent()
        for idx in range(n_objs):
            if idx in objmap:
                continue
            blob = await self._read_from_parent(idx, parent)
            if blob and blob.strip(b"\x00"):
                await self.ioctx.write_full(self._data_oid(idx), blob)
                objmap.add(idx)
        self._hdr["object_map"] = sorted(objmap)
        parent_ref = f"{p['image']}@{p['snap']}"
        self._hdr.pop("parent", None)
        await self._save_header()
        await RBD(self.ioctx)._unregister_child(parent_ref, self.name)

    async def rebuild_object_map(self) -> int:
        """Reconstruct the object map by scanning the pool for this
        image's data objects (reference object_map rebuild operation):
        the recovery path when the header's map was lost or corrupted —
        reads would otherwise treat existing blocks as sparse holes.
        Returns the number of blocks recovered into the map."""
        prefix = f"rbd_data.{self._hdr['id']}."
        found = set()
        for oid in await self.ioctx.list_objects():
            if not oid.startswith(prefix):
                continue
            try:
                found.add(int(oid[len(prefix):]))
            except ValueError:
                continue
        before = set(self._hdr["object_map"])
        n_objs = (self.size + self.object_size - 1) // self.object_size
        rebuilt = {i for i in found if i < n_objs}
        await self._merge_object_map(rebuilt)
        # blocks past the current size stay out of the map (a shrink
        # already trimmed them); blocks the old map falsely claimed are
        # corrected by the authoritative scan
        if before - rebuilt:
            self._hdr["object_map"] = sorted(rebuilt)
            await self._save_header(drop_blocks=sorted(before - rebuilt))
        return len(rebuilt - before)

    async def snap_remove(self, name: str) -> None:
        """Remove a snapshot: the RADOS snap-trim deletes only clones no
        LIVE snap still references (each clone records the snap ids it
        covers), so clones shared with older snapshots survive without
        any service-level re-homing."""
        snap = await self._snap_or_refresh(name)
        snaps = self._snaps()
        if snap is not None and snap.get("protected"):
            raise RbdError(f"snapshot {name!r} is protected")
        if snap is None:
            raise RbdError(f"no snapshot {name!r}")
        # the AUTHORITATIVE protection check is the in-OSD header (a
        # concurrent client may have protected the snap after we opened
        # the image): remove from the header FIRST, release the id after.
        # A failed release then leaks the snap id (space, retried by an
        # operator) — the reverse order could release a PROTECTED snap's
        # id and let snap-trim destroy its clones (data loss).
        got = await self._hdr_cls("snap_remove", {"name": name})
        if got is not None:
            ret, out = got
            if ret == -16:
                raise RbdError(f"snapshot {name!r} is protected")
            if ret not in (0, -2):
                raise RbdError(f"snap_remove failed ({ret})")
            if ret == 0:
                self._hdr = json.loads(out)
            await self.ioctx.release_snap_id(snap["id"])
            return
        await self.ioctx.release_snap_id(snap["id"])
        snaps.pop(name, None)
        await self._save_header()


class RBD:
    """Image management (librbd::RBD role)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    async def create(self, name: str, size: int,
                     order: int = DEFAULT_ORDER) -> Image:
        hdr_oid = Image._header_oid(name)
        header = {"id": uuid.uuid4().hex[:12], "size": size, "order": order,
                  "object_map": []}
        # single in-OSD call (cls_rbd create role): exclusive creation —
        # two racing create()s cannot both win the check-then-write
        try:
            ret, _ = await self.ioctx.execute(
                hdr_oid, "rbd", "create",
                json.dumps({"header": header}).encode())
            if ret == -17:
                raise RbdError(f"image {name!r} exists")
            if ret != 0:
                raise RbdError(f"create failed ({ret})")
            return Image(self.ioctx, name, header)
        except RadosError as e:
            if e.code != -errno.EOPNOTSUPP:
                raise
        try:
            await self.ioctx.read(hdr_oid)
            raise RbdError(f"image {name!r} exists")
        except RadosError as e:
            # only typed absence clears the way: a transient read failure
            # must not let create() overwrite a LIVE header (orphaning its
            # data objects and journal) — same discipline as open()
            if e.code != -errno.ENOENT:
                raise
        await self.ioctx.write_full(hdr_oid, json.dumps(header).encode())
        return Image(self.ioctx, name, header)

    async def open(self, name: str) -> Image:
        try:
            raw = await self.ioctx.read(Image._header_oid(name))
        except RadosError as e:
            # only typed absence means "no image": a transient failure
            # must surface, or callers (the mirrorer!) would treat a
            # blip as image-gone and recreate over live data
            if e.code == -errno.ENOENT:
                raise RbdError(f"image {name!r} does not exist")
            raise
        return Image(self.ioctx, name, json.loads(raw))

    CHILDREN_OID = "rbd_children"  # pool-level clone registry (cls_rbd role)

    async def _children_map(self) -> Dict[str, List[str]]:
        try:
            return json.loads(await self.ioctx.read(self.CHILDREN_OID))
        except RadosError:
            return {}

    async def _register_child(self, parent_ref: str, child: str) -> None:
        cm = await self._children_map()
        kids = cm.setdefault(parent_ref, [])
        if child not in kids:
            kids.append(child)
        await self.ioctx.write_full(self.CHILDREN_OID,
                                    json.dumps(cm).encode())

    async def _unregister_child(self, parent_ref: str, child: str) -> None:
        cm = await self._children_map()
        kids = cm.get(parent_ref, [])
        if child in kids:
            kids.remove(child)
            if not kids:
                cm.pop(parent_ref, None)
            await self.ioctx.write_full(self.CHILDREN_OID,
                                        json.dumps(cm).encode())

    async def children(self, image: str, snap: str) -> List[str]:
        """Clones of image@snap (reference `rbd children`)."""
        return sorted((await self._children_map()).get(f"{image}@{snap}", []))

    async def clone(self, parent: str, snap: str, child: str,
                    order: Optional[int] = None) -> Image:
        """Create a copy-on-write child of a protected parent snapshot
        (reference librbd clone v2).  The child starts with no objects of
        its own: reads fall through to the parent snap, writes copy-up."""
        pimg = await self.open(parent)
        psnap = pimg._snaps().get(snap)
        if psnap is None:
            raise RbdError(f"no snapshot {parent}@{snap}")
        if not psnap.get("protected"):
            raise RbdError(f"snapshot {parent}@{snap} is not protected")
        hdr_oid = Image._header_oid(child)
        try:
            await self.ioctx.read(hdr_oid)
            raise RbdError(f"image {child!r} exists")
        except RadosError:
            pass
        header = {
            "id": uuid.uuid4().hex[:12],
            "size": psnap["size"],
            "order": order if order is not None else pimg._hdr["order"],
            "object_map": [],
            "parent": {"image": parent, "snap": snap, "size": psnap["size"]},
        }
        await self.ioctx.write_full(hdr_oid, json.dumps(header).encode())
        await self._register_child(f"{parent}@{snap}", child)
        return Image(self.ioctx, child, header)

    # -- consistency groups (reference src/librbd/api/Group.cc) -------------
    #
    # A named set of images snapshotted together: the group snapshot is a
    # per-member image snapshot taken under one sweep, named
    # group.<group>.<snap> so member snaps are identifiable and the
    # group object records the membership at snap time.

    @staticmethod
    def _group_oid(group: str) -> str:
        return f"rbd_group.{group}"

    async def _load_group(self, group: str) -> Dict:
        try:
            raw = await self.ioctx.read(self._group_oid(group))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            raise RbdError(f"no group {group!r}") from None
        return json.loads(raw)

    async def _save_group(self, group: str, state: Dict) -> None:
        await self.ioctx.write_full(self._group_oid(group),
                                    json.dumps(state).encode())

    async def group_create(self, group: str) -> None:
        state = {"members": [], "snaps": {}}
        # exclusive creation via the in-OSD class (same discipline as
        # image create: two racing creates must not both win)
        try:
            ret, _ = await self.ioctx.execute(
                self._group_oid(group), "rbd", "create",
                json.dumps({"header": state}).encode())
            if ret == -17:
                raise RbdError(f"group {group!r} exists")
            if ret != 0:
                raise RbdError(f"group create failed ({ret})")
            return
        except RadosError as e:
            if e.code != -errno.EOPNOTSUPP:
                raise
        # EC pool fallback: typed absence check, then write
        exists = True
        try:
            await self._load_group(group)
        except RbdError:
            exists = False
        if exists:
            raise RbdError(f"group {group!r} exists")
        await self._save_group(group, state)

    async def group_remove(self, group: str) -> None:
        state = await self._load_group(group)
        if state["snaps"]:
            raise RbdError(f"group {group!r} has snapshots; remove them")
        await self.ioctx.remove(self._group_oid(group))

    async def group_list(self) -> List[str]:
        pfx = "rbd_group."
        return sorted(o[len(pfx):] for o in await self.ioctx.list_objects()
                      if o.startswith(pfx))

    async def group_image_add(self, group: str, image: str) -> None:
        await self.open(image)  # must exist
        state = await self._load_group(group)
        if image not in state["members"]:
            state["members"].append(image)
            await self._save_group(group, state)

    async def group_image_remove(self, group: str, image: str) -> None:
        state = await self._load_group(group)
        if image in state["members"]:
            state["members"].remove(image)
            await self._save_group(group, state)

    async def group_image_list(self, group: str) -> List[str]:
        return sorted((await self._load_group(group))["members"])

    async def group_snap_create(self, group: str, snap: str) -> None:
        """Snapshot EVERY member at one sweep (the reference quiesces
        via exclusive locks; here member snaps are taken back-to-back on
        one event loop — writes issued after the sweep started land
        after their image's snap, the same point-in-time-per-image
        guarantee a non-quiesced reference group snap gives)."""
        state = await self._load_group(group)
        if snap in state["snaps"]:
            raise RbdError(f"group snapshot {snap!r} exists")
        member_snap = f"group.{group}.{snap}"
        done = []
        try:
            for name in state["members"]:
                img = await self.open(name)
                await img.snap_create(member_snap)
                done.append(name)
        except Exception:
            # partial failure: roll the sweep back so the group snap is
            # all-or-nothing (reference group snap create semantics)
            for name in done:
                try:
                    img = await self.open(name)
                    await img.snap_remove(member_snap)
                except Exception:
                    pass
            raise
        state["snaps"][snap] = {"members": list(state["members"])}
        await self._save_group(group, state)

    async def group_snap_remove(self, group: str, snap: str) -> None:
        state = await self._load_group(group)
        info = state["snaps"].get(snap)
        if info is None:
            raise RbdError(f"no group snapshot {snap!r}")
        member_snap = f"group.{group}.{snap}"
        failed = []
        for name in info["members"]:
            try:
                img = await self.open(name)
            except RbdError:
                continue  # member image since removed: nothing to clean
            try:
                await img.snap_remove(member_snap)
            except RbdError as e:
                if "no snapshot" in str(e):
                    continue  # already gone: idempotent
                failed.append((name, str(e)))
        if failed:
            # keep the group record so the removal can be RETRIED once
            # the blocker clears (e.g. a protected member snap) — popping
            # it would orphan member snaps with no handle left
            raise RbdError(f"group snapshot {snap!r} not fully removed: "
                           f"{failed}")
        state["snaps"].pop(snap)
        await self._save_group(group, state)

    async def group_snap_list(self, group: str) -> List[str]:
        return sorted((await self._load_group(group))["snaps"])

    async def remove(self, name: str) -> None:
        """Remove an image.  Refuses while snapshots exist (reference
        librbd behavior: `rbd snap purge` first)."""
        img = await self.open(name)
        if img._hdr.get("snaps"):
            raise RbdError(f"image {name!r} has snapshots; purge them first")
        for idx in img._hdr["object_map"]:
            try:
                await self.ioctx.remove(img._data_oid(idx))
            except RadosError:
                pass
        p = img._hdr.get("parent")
        if p:
            await self._unregister_child(f"{p['image']}@{p['snap']}", name)
        await self.ioctx.remove(Image._header_oid(name))

    async def snap_purge(self, name: str) -> None:
        img = await self.open(name)
        for snap in list(img.snap_list()):
            await img.snap_remove(snap)

    async def list(self) -> List[str]:
        prefix = "rbd_header."
        return sorted(o[len(prefix):] for o in await self.ioctx.list_objects()
                      if o.startswith(prefix))

    # -- trash (reference librbd trash_* API / `rbd trash`) ------------------
    # Deferred deletion: the header moves to a trash record (the data
    # objects are untouched, keyed by the image id), the image vanishes
    # from list(), and until the deferment window passes it can be
    # restored byte-identically.  Purge deletes expired entries' data.

    @staticmethod
    def _trash_oid(image_id: str) -> str:
        return f"rbd_trash_header.{image_id}"

    async def trash_mv(self, name: str, delay: float = 0.0,
                       now: Optional[float] = None) -> str:
        """Move an image to trash; returns the trash id.  Same snapshot
        guard as remove(): purge snapshots first (divergence: the
        reference allows trashing snapshotted images)."""
        img = await self.open(name)
        if img._hdr.get("snaps"):
            raise RbdError(f"image {name!r} has snapshots; purge them "
                           f"first")
        now = time.time() if now is None else now
        record = {"name": name, "header": img._hdr, "trashed_at": now,
                  "deferment_end": now + max(0.0, delay)}
        image_id = img._hdr["id"]
        await self.ioctx.write_full(self._trash_oid(image_id),
                                    json.dumps(record).encode())
        p = img._hdr.get("parent")
        if p:
            await self._unregister_child(f"{p['image']}@{p['snap']}",
                                         name)
        await self.ioctx.remove(Image._header_oid(name))
        return image_id

    async def trash_ls(self) -> List[Dict]:
        prefix = "rbd_trash_header."
        out = []
        for oid in await self.ioctx.list_objects():
            if not oid.startswith(prefix):
                continue
            try:
                rec = json.loads(await self.ioctx.read(oid))
            except RadosError:
                continue
            out.append({"id": rec["header"]["id"], "name": rec["name"],
                        "trashed_at": rec["trashed_at"],
                        "deferment_end": rec["deferment_end"]})
        return sorted(out, key=lambda r: r["trashed_at"])

    async def _trash_rec(self, image_id: str) -> Dict:
        try:
            return json.loads(await self.ioctx.read(
                self._trash_oid(image_id)))
        except RadosError as e:
            if e.code == -errno.ENOENT:
                raise RbdError(f"no trash entry {image_id!r}")
            raise

    async def trash_restore(self, image_id: str,
                            new_name: Optional[str] = None) -> Image:
        rec = await self._trash_rec(image_id)
        name = new_name or rec["name"]
        if name in await self.list():
            raise RbdError(f"image {name!r} exists; restore under "
                           f"another name")
        await self.ioctx.write_full(Image._header_oid(name),
                                    json.dumps(rec["header"]).encode())
        p = rec["header"].get("parent")
        if p:
            await self._register_child(f"{p['image']}@{p['snap']}", name)
        await self.ioctx.remove(self._trash_oid(image_id))
        return Image(self.ioctx, name, rec["header"])

    async def trash_purge(self, now: Optional[float] = None,
                          force: bool = False) -> int:
        """Delete expired trash entries' data (all entries with
        force=True).  Returns how many images were reclaimed."""
        now = time.time() if now is None else now
        purged = 0
        for entry in await self.trash_ls():
            if not force and now < entry["deferment_end"]:
                continue
            rec = await self._trash_rec(entry["id"])
            hdr = rec["header"]
            img = Image(self.ioctx, rec["name"], hdr)
            for idx in hdr["object_map"]:
                try:
                    await self.ioctx.remove(img._data_oid(idx))
                except RadosError:
                    pass
            await self.ioctx.remove(self._trash_oid(entry["id"]))
            purged += 1
        return purged


# -- image journaling + mirroring (reference src/journal/Journaler.h,
#    src/librbd/mirror/, the rbd-mirror daemon) ------------------------------


class ImageJournal:
    """Per-image write journal (reference journal feature / Journaler):
    every mutating op appends an event BEFORE it applies, into
    length-capped journal segments; a mirror peer replays the events in
    order to reproduce the image bit-for-bit.  Events carry a
    monotonically increasing entry id so replay is resumable and
    idempotent (the mirror records its replay position)."""

    SEGMENT_EVENTS = 256

    def __init__(self, ioctx: IoCtx, image_id: str):
        self.ioctx = ioctx
        self.image_id = image_id
        # appends are read-modify-writes of the segment + head objects:
        # serialized per journal instance.  Cross-INSTANCE writers are the
        # reference's exclusive-lock feature's job (one journaling writer
        # per image at a time); this mirrors that single-writer contract.
        self._append_lock = asyncio.Lock()

    def _head_oid(self) -> str:
        return f"journal.{self.image_id}.head"

    def _seg_oid(self, seg: int) -> str:
        return f"journal.{self.image_id}.{seg:08d}"

    async def _load_head(self) -> Dict:
        try:
            return json.loads(await self.ioctx.read(self._head_oid()))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            return {"next_id": 0, "write_seg": 0, "expire_seg": 0}

    async def append(self, event: Dict) -> int:
        """Append one event; returns its entry id."""
        async with self._append_lock:
            return await self._append_locked(event)

    async def _append_locked(self, event: Dict) -> int:
        head = await self._load_head()
        seg = head["write_seg"]
        oid = self._seg_oid(seg)
        try:
            events = json.loads(await self.ioctx.read(oid))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            events = []
        event = dict(event)
        event["id"] = head["next_id"]
        # persist the HEAD (id reservation + per-segment first-id index)
        # BEFORE the segment: a crash between the two leaves an unused id
        # (a harmless gap) — the reverse order would REUSE an id after
        # restart, and a mirror already past it would skip the event
        # silently forever
        head["next_id"] += 1
        head.setdefault("seg_first", {}).setdefault(str(seg), event["id"])
        if len(events) + 1 >= self.SEGMENT_EVENTS:
            head["write_seg"] += 1
        await self.ioctx.write_full(self._head_oid(),
                                    json.dumps(head).encode())
        events.append(event)
        await self.ioctx.write_full(oid, json.dumps(events).encode())
        return event["id"]

    async def events_after(self, last_id: int) -> List[Dict]:
        """Every event with id > last_id, in order.  The per-segment
        first-id index in the head lets the scan skip fully-replayed
        segments instead of re-reading the whole unexpired journal."""
        head = await self._load_head()
        out: List[Dict] = []
        start = head["expire_seg"]
        seg_first = head.get("seg_first", {})
        for seg in range(head["expire_seg"], head["write_seg"] + 1):
            first = seg_first.get(str(seg))
            if first is not None and first <= last_id:
                start = seg  # last_id lies at/after this segment's start
        for seg in range(start, head["write_seg"] + 1):
            try:
                events = json.loads(await self.ioctx.read(self._seg_oid(seg)))
            except RadosError as e:
                if e.code != -errno.ENOENT:
                    raise
                continue
            out.extend(ev for ev in events if ev["id"] > last_id)
        return out

    async def expire_through(self, entry_id: int) -> None:
        """Drop whole segments whose every event id <= entry_id (mirror
        peers record their positions; the caller passes the minimum)."""
        head = await self._load_head()
        seg = head["expire_seg"]
        changed = False
        while seg < head["write_seg"]:
            try:
                events = json.loads(await self.ioctx.read(self._seg_oid(seg)))
            except RadosError as e:
                if e.code != -errno.ENOENT:
                    raise
                events = []
            if events and events[-1]["id"] > entry_id:
                break
            try:
                await self.ioctx.remove(self._seg_oid(seg))
            except RadosError:
                pass
            seg += 1
            changed = True
        if changed:
            head["expire_seg"] = seg
            await self.ioctx.write_full(self._head_oid(),
                                        json.dumps(head).encode())


class JournaledImage:
    """An Image whose writes/resizes journal before applying (the rbd
    journaling feature): wrap an open Image; mutations append an event,
    then apply.  Reads pass through."""

    def __init__(self, image: Image):
        self.image = image
        self.journal = ImageJournal(image.ioctx, image._hdr["id"])

    @property
    def size(self) -> int:
        return self.image.size

    async def write(self, offset: int, data: bytes) -> None:
        # validate BEFORE journaling: a write the primary would refuse
        # must never reach the journal, or the mirror (which auto-grows)
        # would apply bytes the primary never accepted
        if offset + len(data) > self.image.size:
            raise RbdError("write beyond image size (resize first)")
        await self.journal.append({"op": "write", "offset": offset,
                                   "data": data.hex()})
        await self.image.write(offset, data)

    async def resize(self, new_size: int) -> None:
        await self.journal.append({"op": "resize", "size": new_size})
        await self.image.resize(new_size)

    async def read(self, offset: int, length: int) -> bytes:
        return await self.image.read(offset, length)


class Mirrorer:
    """rbd-mirror daemon role (reference src/librbd/mirror/ +
    src/tools/rbd_mirror): replays a primary image's journal into a
    peer image, resumably — the replay position persists in the peer
    pool so a restarted mirrorer continues where it left off."""

    def __init__(self, src_ioctx: IoCtx, dst_ioctx: IoCtx):
        self.src = src_ioctx
        self.dst = dst_ioctx

    def _pos_oid(self, image_id: str) -> str:
        return f"rbd_mirror.pos.{image_id}"

    def _peers_oid(self, image_id: str) -> str:
        # lives in the SRC pool: every peer's replay position, so journal
        # expiry advances only past what EVERY registered peer replayed
        return f"rbd_mirror.peers.{image_id}"

    async def _load_pos(self, image_id: str) -> int:
        try:
            return json.loads(await self.dst.read(self._pos_oid(image_id)))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            return -1

    async def _update_peer_positions(self, image_id: str,
                                     pos: int) -> int:
        """Record this peer's position in the src pool; returns the
        MINIMUM across peers (the safe expiry floor)."""
        oid = self._peers_oid(image_id)
        try:
            peers = json.loads(await self.src.read(oid))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            peers = {}
        peers[f"pool{self.dst.pool_id}"] = pos
        await self.src.write_full(oid, json.dumps(peers).encode())
        return min(peers.values())

    async def replay(self, name: str) -> int:
        """Replay new journal events of src image `name` into the dst
        pool's image of the same name (created on first replay).
        Returns the number of events applied."""
        src_img = await RBD(self.src).open(name)
        journal = ImageJournal(self.src, src_img._hdr["id"])
        dst_rbd = RBD(self.dst)
        try:
            dst_img = await dst_rbd.open(name)
        except RbdError:
            dst_img = await dst_rbd.create(
                name, src_img.size, order=src_img._hdr["order"])
        pos = await self._load_pos(src_img._hdr["id"])
        if pos < 0:
            # first contact (rbd-mirror initial image sync): journal
            # events before now may already be expired for other peers,
            # so copy the CURRENT image content, then tail the journal
            # from the newest reserved id
            head = await journal._load_head()
            content = await src_img.read(0, src_img.size)
            if dst_img.size != src_img.size:
                await dst_img.resize(src_img.size)
            await dst_img.write(0, content)
            pos = head["next_id"] - 1
            await self.dst.write_full(self._pos_oid(src_img._hdr["id"]),
                                      json.dumps(pos).encode())
            await self._update_peer_positions(src_img._hdr["id"], pos)
        events = await journal.events_after(pos)
        applied = 0
        for ev in events:
            if ev["op"] == "write":
                data = bytes.fromhex(ev["data"])
                if ev["offset"] + len(data) > dst_img.size:
                    await dst_img.resize(ev["offset"] + len(data))
                await dst_img.write(ev["offset"], data)
            elif ev["op"] == "resize":
                await dst_img.resize(ev["size"])
            pos = ev["id"]
            applied += 1
        if applied:
            await self.dst.write_full(self._pos_oid(src_img._hdr["id"]),
                                      json.dumps(pos).encode())
            floor = await self._update_peer_positions(
                src_img._hdr["id"], pos)
            await journal.expire_through(floor)
        return applied


class ImageMigrator:
    """Live image migration between pools (reference src/librbd/migration/):
    prepare -> execute -> commit, with abort at any point before commit.

    prepare() creates the destination image and marks BOTH headers with
    the migration link; execute() copies the head and re-materializes
    every snapshot's content at the destination (point-in-time copies —
    destination snap ids are fresh, as the reference's snapshot-copy
    phase produces); commit() verifies the copy, drops the links, and
    removes the source; abort() removes the destination and clears the
    source's link.  The source stays readable throughout (migration is a
    background copy, not a cut-over), matching the reference's
    read-from-source-until-commit behavior."""

    def __init__(self, src_ioctx: IoCtx, dst_ioctx: IoCtx):
        self.src_rbd = RBD(src_ioctx)
        self.dst_rbd = RBD(dst_ioctx)

    async def prepare(self, name: str) -> None:
        src = await self.src_rbd.open(name)
        if src._hdr.get("migration"):
            raise RbdError(f"image {name!r} is already migrating")
        if src._hdr.get("parent"):
            # a clone's parent-backed blocks are not in its object map;
            # the block copier would silently migrate zeros there
            raise RbdError(f"image {name!r} is a clone; flatten it "
                           f"before migrating")
        dst = await self.dst_rbd.create(name, src.size,
                                        order=src._hdr["order"])
        dst._hdr["migration"] = {"role": "destination", "state": "prepared"}
        await dst._save_header()
        src._hdr["migration"] = {"role": "source", "state": "prepared"}
        await src._save_header()

    @staticmethod
    async def _sync_block_set(dst: Image, keep) -> None:
        """DEALLOCATE destination blocks absent from the source's map for
        this pass: a snapshot (or head) whose map shrank between passes
        must not expose the previous pass's bytes where the source reads
        zeros.  Removal (the resize-shrink pattern) keeps holes holes —
        zero-WRITES would materialize the blocks and make every later
        pass re-process them."""
        keep = set(keep)
        extra = sorted(set(dst._hdr["object_map"]) - keep)
        if not extra:
            return
        snapc = dst._image_snapc()
        for idx in extra:
            try:
                await dst.ioctx.remove(dst._data_oid(idx), snapc=snapc)
            except RadosError:
                pass
        dst._hdr["object_map"] = sorted(
            set(dst._hdr["object_map"]) - set(extra))
        await dst._save_header(drop_blocks=extra)

    @staticmethod
    async def _copy_blocks(read_at, dst: Image, size: int,
                           blocks) -> None:
        """Block-granular copy: bounded memory for any image size, and
        holes stay holes (only the source's materialized blocks are
        written, so a sparse source does not become a fully-allocated
        destination)."""
        bs = dst.object_size
        for idx in sorted(blocks):
            base = idx * bs
            if base >= size:
                continue
            n = min(bs, size - base)
            await dst.write(base, await read_at(base, n))

    async def execute(self, name: str) -> None:
        src = await self.src_rbd.open(name)
        dst = await self.dst_rbd.open(name)
        mig = src._hdr.get("migration")
        if not mig or mig.get("role") != "source":
            raise RbdError(f"image {name!r} is not migration-prepared")
        # snapshots first, OLDEST to newest: each snap's content is
        # written then snapped at the destination, rebuilding the
        # point-in-time history before the head lands on top.
        # Idempotent: a re-execute after a failed commit skips snapshots
        # the first pass already rebuilt (commit's advertised recovery).
        existing = set(dst.snap_list())
        snaps = sorted(src._snaps().items(), key=lambda kv: kv[1]["id"])
        for snap_name, info in snaps:
            if snap_name in existing:
                continue
            if dst.size != info["size"]:
                await dst.resize(info["size"])
            await self._sync_block_set(dst, info.get("object_map", ()))
            await self._copy_blocks(
                lambda off, n, s=snap_name: src.read_snap(s, off, n),
                dst, info["size"], info.get("object_map", ()))
            await dst.snap_create(snap_name)
            if info.get("protected"):
                await dst.snap_protect(snap_name)
        if dst.size != src.size:
            await dst.resize(src.size)
        await self._sync_block_set(dst, src._hdr["object_map"])
        await self._copy_blocks(src.read, dst, src.size,
                                src._hdr["object_map"])
        dst._hdr["migration"] = {"role": "destination", "state": "executed"}
        await dst._save_header()

    async def commit(self, name: str) -> None:
        dst = await self.dst_rbd.open(name)
        try:
            src = await self.src_rbd.open(name)
        except RbdError:
            # crash-resume: the source was already torn down by a prior
            # commit that died before unmarking the destination — finish
            # that last step
            if dst._hdr.get("migration", {}).get("state") == "executed":
                dst._hdr.pop("migration", None)
                await dst._save_header()
                return
            raise
        if dst._hdr.get("migration", {}).get("state") != "executed":
            raise RbdError(f"migration of {name!r} has not executed")
        # ALL validation before ANY destructive step: sizes line up,
        # every SOURCE snapshot exists at the destination, and no source
        # snapshot has clone children (teardown would wedge otherwise).
        # Subset, not equality: a commit that crashed mid-source-teardown
        # resumes with some source snaps already gone — the destination
        # holding MORE history than the torn source is the expected
        # resumable state, not a validation failure.
        if dst.size != src.size or not set(src.snap_list()) <= \
                set(dst.snap_list()):
            raise RbdError(f"migration of {name!r} failed validation; "
                           f"abort or re-execute")
        for snap in src.snap_list():
            children = await self.src_rbd.children(name, snap)
            if children:
                raise RbdError(
                    f"source snapshot {snap!r} has clone children "
                    f"{children}; flatten them before committing")
        # final catch-up pass: writes that landed on the source AFTER
        # execute() are re-copied now — and blocks the source trimmed
        # since execute are deallocated — so commit is a full sync point,
        # not a silent cutoff (the reference's commit-time final sync
        # role); sizes were validated equal above
        await self._sync_block_set(dst, src._hdr["object_map"])
        await self._copy_blocks(src.read, dst, src.size,
                                src._hdr["object_map"])
        # teardown order matters for crash recovery: the source dies
        # FIRST and the destination is unmarked LAST, so a crash at any
        # point leaves a state commit() can resume from (src-gone +
        # dst-executed = the resume branch above); the reverse order
        # would strand a marked source no API call can clear
        for snap in list(src.snap_list()):
            snap_obj = src._snaps().get(snap, {})
            if snap_obj.get("protected"):
                await src.snap_unprotect(snap)
            await src.snap_remove(snap)
        src = await self.src_rbd.open(name)
        src._hdr.pop("migration", None)
        await src._save_header()
        await self.src_rbd.remove(name)
        dst._hdr.pop("migration", None)
        await dst._save_header()

    async def abort(self, name: str) -> None:
        dst = None
        try:
            dst = await self.dst_rbd.open(name)
        except RbdError:
            pass  # destination never created: abort is idempotent
        if dst is not None:
            if dst._hdr.get("migration", {}).get("role") != "destination":
                # a same-named image that was never a migration
                # destination must NOT be torn down by an aborted (or
                # mistyped) migration
                raise RbdError(
                    f"image {name!r} in the destination pool is not a "
                    f"migration destination; refusing to remove it")
            # teardown failures SURFACE (the destination stays marked and
            # abort can be retried) — swallowing them would clear the
            # source link below and wedge the half-removed destination
            for snap in list(dst.snap_list()):
                snap_obj = dst._snaps().get(snap, {})
                if snap_obj.get("protected"):
                    await dst.snap_unprotect(snap)
                await dst.snap_remove(snap)
            dst = await self.dst_rbd.open(name)
            dst._hdr.pop("migration", None)
            await dst._save_header()
            await self.dst_rbd.remove(name)
        src = await self.src_rbd.open(name)
        if src._hdr.pop("migration", None) is not None:
            await src._save_header()
