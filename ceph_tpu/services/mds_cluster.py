"""Multi-active MDS: subtree partitioning, migration, and balancing.

Role-equivalent of the reference's multi-MDS metadata cluster (reference
src/mds/Migrator.cc subtree export/import, src/mds/MDBalancer.cc load
balancing, src/mds/MDSMap.h rank table): the namespace is partitioned by
DIRECTORY SUBTREE across N active ranks, each rank serializes and
journals mutations for the subtrees it is authoritative over, and
authority over a subtree can MIGRATE between ranks online.

TPU-first simplifications that keep the semantics honest:

- dirfrags live in shared RADOS objects, so migration moves AUTHORITY
  (who may mutate + grant caps), never data — the same property the
  reference gets from metadata-in-RADOS;
- the export protocol is two-phase against a persisted subtree map:
  freeze -> revoke caps under the subtree -> drain+flush the exporter's
  journal -> persist a pending record -> commit the map.  A crash
  between pending and commit is completed at next start() (the
  reference's EExport/EImportStart journal pair in miniature);
- cap/lease state is volatile per rank (the reference journals it in
  ESessions; here clients re-acquire after a rank replacement, the
  up:reconnect stage).

Single-rank deployments are unchanged: MDSCluster(n_ranks=1) behaves
exactly like a lone MDSServer.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import posixpath
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx
from ceph_tpu.services.mds import (CephFSClient, FileSystem, FsError,
                                   MDSServer, is_under as _is_under,
                                   open_file)

SUBTREE_MAP_OID = "mds_subtree_map"


def _norm(path: str) -> str:
    return FileSystem._norm(path)


class MDSCluster:
    """N active MDS ranks over one metadata/data pool pair.

    The subtree map (persisted at SUBTREE_MAP_OID) assigns each subtree
    root to a rank; a path's authority is the DEEPEST matching root (the
    reference resolves auth the same way through its subtree bounds).
    """

    def __init__(self, meta_ioctx: IoCtx, data_ioctx: Optional[IoCtx] = None,
                 n_ranks: int = 2, session_timeout: float = 60.0,
                 revoke_timeout: float = 5.0):
        self.meta = meta_ioctx
        self.data = data_ioctx or meta_ioctx
        self.n_ranks = int(n_ranks)
        self.session_timeout = session_timeout
        self.revoke_timeout = revoke_timeout
        self.epoch = 0
        self.subtrees: Dict[str, int] = {"/": 0}
        self.ranks: List[MDSServer] = []
        self._frozen: set = set()      # subtree roots mid-export
        self.rank_ops: List[int] = []  # balancer heat, per rank
        self._dir_ops: Dict[str, int] = {}  # top-level dir -> ops
        # serializes TOPOLOGY-changing operations (subtree export and
        # directory rename): a directory rename racing an export could
        # otherwise commit a subtree root whose path just moved
        self._topology = asyncio.Lock()

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "MDSCluster":
        """Load (or create) the subtree map, start every rank (each
        replays its OWN journal — up:replay), and resolve any export
        that was cut down mid-flight."""
        try:
            m = json.loads(await self.meta.read(SUBTREE_MAP_OID))
            self.epoch = m["epoch"]
            self.subtrees = {p: int(r) for p, r in m["subtrees"].items()}
            pending = m.get("pending")
        except RadosError as e:
            import errno as _errno
            # a fresh map is only right for VERIFIED absence: writing
            # the default over a transiently-unreadable real map would
            # silently revert every migrated subtree to rank 0
            if e.code != -_errno.ENOENT:
                raise
            pending = None
            await self._save_map(pending=None)
        self.ranks = []
        for r in range(self.n_ranks):
            fs = FileSystem(self.meta, self.data,
                            journal_prefix=f"mds{r}.")
            if r == 0:
                await fs.mkfs()
            await fs.mount()
            self.ranks.append(MDSServer(fs, self.session_timeout))
        self.rank_ops = [0] * self.n_ranks
        if pending is not None:
            # the exporter flushed its journal BEFORE the pending record
            # was persisted, so completing the map flip is always safe
            # (EImportFinish replay role)
            self.subtrees[pending["path"]] = int(pending["to"])
            await self._save_map(pending=None)
        for r in range(self.n_ranks):
            async with self.ranks[r].fs._mutate:
                await self._reconcile_renames(r)
        return self

    async def _save_map(self, pending) -> None:
        self.epoch += 1
        await self.meta.write_full(SUBTREE_MAP_OID, json.dumps(
            {"epoch": self.epoch, "subtrees": self.subtrees,
             "pending": pending}).encode())

    # -- authority resolution ------------------------------------------------

    def rank_of(self, path: str) -> int:
        path = _norm(path)
        best, best_len = 0, -1
        for root, rank in self.subtrees.items():
            if _is_under(path, root):
                depth = 0 if root == "/" else root.count("/")
                if depth > best_len:
                    best, best_len = rank, depth
        return best

    def server(self, rank: int) -> MDSServer:
        return self.ranks[rank]

    def _check_frozen(self, path: str) -> None:
        path = _norm(path)
        for root in self._frozen:
            if _is_under(path, root):
                raise FsError(f"EAGAIN: subtree {root} migrating")

    def route(self, path: str) -> Tuple[int, MDSServer]:
        """(rank, authoritative server) for `path`, with balancer heat
        accounting; raises retryable EAGAIN while the covering subtree
        is mid-export (the reference freezes the exported CDir the same
        way)."""
        self._check_frozen(path)
        rank = self.rank_of(path)
        self.rank_ops[rank] += 1
        p = _norm(path)
        top = "/" + p.split("/")[1] if p != "/" else "/"
        self._dir_ops[top] = self._dir_ops.get(top, 0) + 1
        return rank, self.ranks[rank]

    # -- subtree migration (Migrator role) -----------------------------------

    async def export_dir(self, path: str, to_rank: int) -> None:
        """Move authority over the subtree at `path` to `to_rank`:
        freeze -> revoke caps -> drain + flush exporter journal ->
        persist pending -> commit map -> thaw.  Holds the topology lock
        so a concurrent directory rename cannot move the path out from
        under the commit."""
        path = _norm(path)
        if not (0 <= to_rank < self.n_ranks):
            raise FsError(f"EINVAL: no rank {to_rank}")
        from_rank = self.rank_of(path)
        if from_rank == to_rank:
            return
        src = self.ranks[from_rank]
        st = await src.fs.stat(path)
        if st["type"] != "dir":
            raise FsError(f"ENOTDIR: {path}")
        if path in self._frozen:
            raise FsError(f"EAGAIN: {path} already migrating")
        self._frozen.add(path)
        try:
            # the revoke wait (up to revoke_timeout of client
            # compliance) runs OUTSIDE the topology lock: one slow
            # client must not stall unrelated exports/renames
            await self._revoke_subtree_caps(src, path)
            # drain in-flight mutations, then flush: roll closes the
            # write segment so expire retires EVERY applied event —
            # without the roll, current-segment events survive and a
            # later replace_rank() of the exporter would replay them
            # onto dirfrags the importer has since rewritten.  The
            # re-validation + map commit hold topology + rank locks:
            # a directory rename (which takes the same pair) cannot
            # move the path between them.
            async with self._topology:
                # re-resolve UNDER the lock: an ancestor export that
                # committed while we waited may have moved authority —
                # draining the stale rank's journal would leave the
                # real authority's in-flight events undrained
                if self.rank_of(path) != from_rank:
                    raise FsError(f"EAGAIN: authority of {path} moved "
                                  f"during export; retry")
                async with src.fs._mutate:
                    if src.fs.mdlog is not None:
                        await src.fs.mdlog.roll()
                        await src.fs.mdlog.expire()
                    if await src.fs._load_dir(path) is None:
                        raise FsError(f"EAGAIN: {path} vanished "
                                      f"before export commit")
                    # two-phase commit against the persisted map
                    await self._save_map(
                        pending={"path": path, "to": to_rank})
                    self.subtrees[path] = to_rank
                    await self._save_map(pending=None)
        finally:
            self._frozen.discard(path)

    async def _revoke_subtree_caps(self, src: MDSServer, root: str) -> None:
        """Queue revokes for every cap under the subtree and wait for
        the holders to comply (flush + release on their next renew).
        Holders that never comply within revoke_timeout are evicted —
        the session-autoclose semantics the reference applies to
        unresponsive clients."""
        deadline = time.monotonic() + self.revoke_timeout
        while True:
            live = []
            for path, holders in list(src._caps.items()):
                if not _is_under(path, root):
                    continue
                for sid in list(holders):
                    if src._evict_if_dead(sid):
                        continue
                    sess = src.sessions[sid]
                    if path not in sess.revoked:
                        sess.revoked.append(path)
                    live.append((path, sid))
            if not live:
                return
            if time.monotonic() >= deadline:
                # forcible eviction: identical outcome to lease expiry
                for path, sid in live:
                    src._drop(path, sid)
                return
            await asyncio.sleep(0.02)

    # -- snapshots (snapserver seat: rank 0) ---------------------------------

    @contextlib.asynccontextmanager
    async def _all_ranks_barrier(self):
        """Hold every rank's mutation lock (stable id order, matching
        the cross-rank rename's two-lock ordering, so the two cannot
        deadlock against each other)."""
        locks = sorted((r.fs for r in self.ranks), key=id)
        async with contextlib.AsyncExitStack() as stack:
            for fs in locks:
                await stack.enter_async_context(fs._mutate)
            yield

    async def snap_create(self, path: str, name: str) -> None:
        async with self._all_ranks_barrier():
            await self.ranks[0].fs._snap_create_locked(path, name)
            for r in self.ranks:
                r.fs.invalidate_snap_cache()

    async def snap_delete(self, path: str, name: str) -> None:
        async with self._all_ranks_barrier():
            await self.ranks[0].fs._snap_delete_locked(path, name)
            for r in self.ranks:
                r.fs.invalidate_snap_cache()

    def _guard_dir_move(self, src_path: str) -> None:
        """A directory move must not carry (or be) a SUBTREE ROOT — the
        map keys authority by path, so the root would dangle; export
        authority away first (EXDEV, the reference's unmovable subtree
        bounds).  Call with the topology lock held."""
        for root in self.subtrees:
            if root != "/" and _is_under(root, src_path):
                raise FsError(f"EXDEV: {src_path} contains/is subtree "
                              f"root {root}; move authority first")

    # -- cross-rank rename intent log ----------------------------------------
    # One log object per SOURCE rank ("mds<r>.rename_log"): an entry is
    # persisted BEFORE either dentry half mutates and removed after both
    # landed, so a crash between the two journal appends leaves a
    # durable intent that reconciliation completes (the reference's
    # EPeerUpdate prepare/commit pair in miniature).  All mutations of a
    # rank's log happen while holding that rank's _mutate lock.

    def _rename_log_oid(self, rank: int) -> str:
        return f"mds{rank}.rename_log"

    async def _load_rename_log(self, rank: int) -> List[Dict]:
        try:
            return json.loads(await self.meta.read(
                self._rename_log_oid(rank)))
        except RadosError as e:
            import errno as _errno
            if e.code != -_errno.ENOENT:
                raise
            return []

    async def _save_rename_log(self, rank: int,
                               entries: List[Dict]) -> None:
        await self.meta.write_full(self._rename_log_oid(rank),
                                   json.dumps(entries).encode())

    async def _reconcile_renames(self, rank: int,
                                 fs_override: Optional[FileSystem] = None
                                 ) -> None:
        """Complete (or discard) cross-rank renames whose source half
        may not have landed.  If the destination dentry shows the
        rename committed, the stale source dentry is removed — through
        the SOURCE rank's own journal; dirfrags of other ranks are only
        ever read."""
        entries = await self._load_rename_log(rank)
        if not entries:
            return
        fs_src = fs_override or self.ranks[rank].fs
        for e in list(entries):
            fs_dst = self.ranks[e["dst_rank"]].fs
            ddentries = await fs_dst._load_dir(e["dparent"])
            committed = bool(ddentries) and \
                ddentries.get(e["dname"], {}).get("ino") == e["ino"]
            if committed:
                sdentries = await fs_src._load_dir(e["sparent"])
                if sdentries is not None and \
                        sdentries.get(e["sname"], {}).get("ino") == e["ino"]:
                    ev = {"op": "rename", "events": [
                        {"op": "rm_dentry", "parent": e["sparent"],
                         "name": e["sname"]}]}
                    await fs_src._journal(ev)
                    await fs_src._apply_event(ev)
                    await fs_src._journal_applied()
            # not committed: the rename never happened — source stays
            entries.remove(e)
        await self._save_rename_log(rank, entries)

    # -- rank failure / replacement ------------------------------------------

    async def replace_rank(self, rank: int) -> MDSServer:
        """Stand up a replacement for a failed rank: a fresh server
        mounts the SAME per-rank journal and replays it (up:replay),
        then completes any cross-rank rename whose source half the
        crash cut short, then serves.  Sessions/caps are gone — clients
        reconnect (up:reconnect is client-driven here)."""
        fs = FileSystem(self.meta, self.data, journal_prefix=f"mds{rank}.")
        await fs.mount()
        async with fs._mutate:
            await self._reconcile_renames(rank, fs_override=fs)
        self.ranks[rank] = MDSServer(fs, self.session_timeout)
        return self.ranks[rank]

    # -- balancing (MDBalancer role) -----------------------------------------

    async def maybe_rebalance(self, ratio: float = 2.0) -> Optional[Tuple]:
        """If the hottest rank carries > `ratio` x the coldest rank's
        ops, export the hottest top-level subtree it owns to the coldest
        rank.  Returns (path, from, to) when a migration ran."""
        if self.n_ranks < 2 or not any(self.rank_ops):
            return None
        hot = max(range(self.n_ranks), key=lambda r: self.rank_ops[r])
        cold = min(range(self.n_ranks), key=lambda r: self.rank_ops[r])
        if self.rank_ops[hot] < ratio * max(1, self.rank_ops[cold]):
            return None
        candidates = [
            (ops, d) for d, ops in self._dir_ops.items()
            if d != "/" and self.rank_of(d) == hot
        ]
        if not candidates:
            return None
        _ops, path = max(candidates)
        try:
            if (await self.ranks[hot].fs.stat(path))["type"] != "dir":
                return None
        except FsError:
            return None
        await self.export_dir(path, cold)
        self.rank_ops = [0] * self.n_ranks
        self._dir_ops.clear()
        return (path, hot, cold)

    # -- cross-rank rename ---------------------------------------------------

    async def rename(self, src_path: str, dst_path: str) -> None:
        """Rename whose source and destination live under different
        authorities (the reference's slave-request rename): both ranks'
        mutation locks are held (rank order, so two concurrent cross
        renames cannot deadlock), the intent is journaled at the SOURCE
        rank as one event, and both dirfrag halves are applied under the
        locks.  Same-rank renames route normally."""
        src_path, dst_path = _norm(src_path), _norm(dst_path)
        self._check_frozen(src_path)
        self._check_frozen(dst_path)
        r_src, r_dst = self.rank_of(src_path), self.rank_of(dst_path)
        if r_src == r_dst:
            server = self.ranks[r_src]
            is_dir = False
            try:
                is_dir = (await server.fs.stat(src_path))["type"] == "dir"
            except FsError:
                pass
            if is_dir:
                # other sessions' caps under the moving tree must be
                # revoked first (their write-behind would flush into
                # dead paths) — same compliance wait as export
                await self._revoke_subtree_caps(server, src_path)
            async with self._topology:
                # re-resolve UNDER the lock (same discipline as
                # export_dir and the cross-rank branch below): an
                # export that committed while the revoke wait ran may
                # have moved authority — renaming via the stale rank
                # would mutate dirfrags the new authority owns outside
                # its _mutate lock.
                self._check_frozen(src_path)
                self._check_frozen(dst_path)
                if (self.rank_of(src_path) != r_src
                        or self.rank_of(dst_path) != r_src):
                    raise FsError(
                        f"EAGAIN: authority of {src_path} or {dst_path} "
                        "moved during rename lock wait")
                if is_dir:
                    self._guard_dir_move(src_path)
                await server.fs.rename(src_path, dst_path)
            if is_dir:
                # caps under either tree now name dead paths
                for p in list(server._caps):
                    if _is_under(p, src_path) or _is_under(p, dst_path):
                        for sid in list(server._caps.get(p, {})):
                            server._drop(p, sid)
            return
        fs_src, fs_dst = self.ranks[r_src].fs, self.ranks[r_dst].fs
        first, second = sorted((fs_src, fs_dst), key=id)
        async with first._mutate:
            async with second._mutate:
                # re-resolve UNDER the locks (mirror export_dir): a
                # subtree export may have committed while we waited, in
                # which case journaling dentry mutations at the stale
                # ranks would mutate dirfrags outside the new
                # authority's _mutate lock — lost updates, and a later
                # replace_rank() would replay them onto importer-owned
                # dirfrags.  Retryable EAGAIN, same as export_dir.
                self._check_frozen(src_path)
                self._check_frozen(dst_path)
                if (self.rank_of(src_path) != r_src
                        or self.rank_of(dst_path) != r_dst):
                    raise FsError(
                        f"EAGAIN: authority of {src_path} or {dst_path} "
                        "moved during rename lock wait")
                sparent = posixpath.dirname(src_path)
                sname = posixpath.basename(src_path)
                sdentries = await fs_src._load_dir(sparent)
                if sdentries is None or sname not in sdentries:
                    raise FsError(f"ENOENT: {src_path}")
                ent = sdentries[sname]
                if ent["type"] == "dir":
                    raise FsError("EXDEV: cross-rank directory rename "
                                  "unsupported; export the subtree "
                                  "instead")
                dparent = posixpath.dirname(dst_path)
                dname = posixpath.basename(dst_path)
                ddentries = await fs_dst._load_dir(dparent)
                if ddentries is None:
                    raise FsError(f"ENOENT: parent {dparent}")
                if ddentries.get(dname, {}).get("type") == "dir":
                    raise FsError(f"EISDIR: {dst_path}")
                # each HALF is journaled at the rank that owns its
                # dirfrag, destination first (set) then source (rm), so
                # each rank's replay touches ONLY its own dirfrags and
                # replaying one rank never races the live peer's
                # read-modify-writes.  The durable INTENT goes to the
                # source rank's rename log FIRST: a crash between the
                # two halves leaves a record that reconciliation uses to
                # finish the source removal — without it the stale
                # source dentry would share the inode with the renamed
                # file forever, and unlinking it would destroy the data.
                intent = {"ino": ent.get("ino"), "sparent": sparent,
                          "sname": sname, "dparent": dparent,
                          "dname": dname, "dst_rank": r_dst}
                log = await self._load_rename_log(r_src)
                log.append(intent)
                await self._save_rename_log(r_src, log)
                dst_subs = [{"op": "set_dentry", "parent": dparent,
                             "name": dname, "dentry": ent}]
                old = ddentries.get(dname)
                if (old and old.get("ino") and old["ino"] != ent.get("ino")
                        and old["ino"] not in fs_dst._snap_inos(
                            await fs_dst._load_snaptable(use_cache=True))):
                    dst_subs.append({"op": "drop_ino", "ino": old["ino"]})
                dst_event = {"op": "rename", "events": dst_subs}
                src_event = {"op": "rename", "events": [
                    {"op": "rm_dentry", "parent": sparent,
                     "name": sname}]}
                await fs_dst._journal(dst_event)
                await fs_dst._apply_event(dst_event)
                await fs_dst._journal_applied()
                await fs_src._journal(src_event)
                await fs_src._apply_event(src_event)
                await fs_src._journal_applied()
                log = [e for e in await self._load_rename_log(r_src)
                       if e != intent]
                await self._save_rename_log(r_src, log)


class CephFSMultiClient:
    """Client facade over an MDSCluster: one cap-aware CephFSClient per
    rank, each op routed to the path's authoritative rank.  Frozen
    subtrees (mid-export) are retried; the retry loop renews EVERY
    per-rank session so pending revokes get complied with — which is
    exactly what lets the exporter finish."""

    def __init__(self, cluster: MDSCluster, client: str = "client",
                 renew_interval: float = 1.0):
        self.cluster = cluster
        self.name = client
        self.client_name = client  # identity for open-time permission
        self.renew_interval = renew_interval
        self._clients: Dict[int, CephFSClient] = {}

    def _client_for(self, rank: int) -> CephFSClient:
        c = self._clients.get(rank)
        if c is None or c.session.session_id not in \
                self.cluster.ranks[rank].sessions:
            # first contact, or the rank was replaced (sessions are
            # volatile): open a fresh session — up:reconnect role
            c = CephFSClient(self.cluster.ranks[rank], self.name,
                             self.renew_interval)
            self._clients[rank] = c
        return c

    async def _handoff(self, path: str, rank: int) -> None:
        """Cache handoff after a migration: write-behind bytes staged at
        a rank that is no longer the path's authority are re-staged at
        the new one (the reference client re-targets its caps to the
        importing MDS after an export).  Without this, dirty data from
        before a forced cap drop would be stranded — or worse, flushed
        through the stale authority."""
        from ceph_tpu.services.mds import FileSystem
        p = FileSystem._norm(path)
        for r, c in list(self._clients.items()):
            if r == rank:
                continue
            data = c._dirty.pop(p, None)
            c._clean.pop(p, None)
            if p in c.session.caps:
                c.mds.release_cap(c.session, p)
            if data is not None:
                await self._client_for(rank).write(p, data)

    async def _routed(self, path: str, op: str, *args,
                      retries: int = 100, delay: float = 0.02):
        for attempt in range(retries):
            try:
                rank, _server = self.cluster.route(path)
                await self._handoff(path, rank)
                return await getattr(self._client_for(rank), op)(
                    path, *args)
            except FsError as e:
                if "EAGAIN" not in str(e) or attempt == retries - 1:
                    raise
                await self.renew_all()
                await asyncio.sleep(delay)

    async def renew_all(self) -> None:
        for c in list(self._clients.values()):
            await c.renew()

    async def write(self, path: str, data: bytes) -> None:
        await self._routed(path, "write", data)

    async def read(self, path: str) -> bytes:
        return await self._routed(path, "read")

    # -- file handles (libcephfs ll_open surface over the cluster) -----------

    async def pread(self, path: str, off: int, n: int = -1) -> bytes:
        return await self._routed(path, "pread", off, n)

    async def pwrite(self, path: str, off: int, data: bytes) -> int:
        return await self._routed(path, "pwrite", off, data)

    async def append(self, path: str, data: bytes) -> int:
        return await self._routed(path, "append", data)

    async def truncate(self, path: str, size: int) -> None:
        await self._routed(path, "truncate", size)

    async def chmod(self, path: str, mode: int) -> None:
        await self._routed(path, "chmod", mode)

    async def open(self, path: str, mode: str = "r"):
        """Open a handle whose every operation re-routes to the path's
        CURRENT authoritative rank — a subtree export mid-handle just
        redirects the next op (with cache handoff), it does not
        invalidate the handle."""
        return await open_file(self, path, mode)

    async def fsync(self, path: str) -> None:
        await self._routed(path, "fsync")

    async def mkdir(self, path: str) -> None:
        await self._routed(path, "mkdir")

    async def listdir(self, path: str) -> List[str]:
        return await self._routed(path, "listdir")

    async def stat(self, path: str) -> Dict:
        return await self._routed(path, "stat")

    async def unlink(self, path: str) -> None:
        await self._routed(path, "unlink")

    async def rename(self, src: str, dst: str,
                     retries: int = 100, delay: float = 0.02) -> None:
        """Same-rank renames (files AND directories) go through the
        authoritative rank's SERVER, so cap holders under a moving
        directory are forced to comply first; the topology lock keeps
        directory moves ordered against subtree exports.  Cross-rank
        renames (files only) take the cluster's two-lock path.  The
        SOURCE's write-behind is flushed first; DESTINATION caches are
        dropped WITHOUT flushing — the rename clobbers that content by
        definition.  Frozen subtrees retry like every other facade op."""
        s, d = _norm(src), _norm(dst)
        for attempt in range(retries):
            try:
                self.cluster._check_frozen(s)
                self.cluster._check_frozen(d)
                r_src, r_dst = self.cluster.rank_of(s), \
                    self.cluster.rank_of(d)
                if r_src == r_dst:
                    async with self.cluster._topology:
                        try:
                            st = await self.cluster.ranks[
                                r_src].fs.stat(s)
                        except FsError:
                            st = {}
                        if st.get("type") == "dir":
                            self.cluster._guard_dir_move(s)
                        await self._handoff(s, r_src)
                        await self._client_for(r_src).rename(s, d)
                else:
                    await self._routed(s, "fsync")
                    for c in self._clients.values():
                        c._dirty.pop(d, None)
                        c._clean.pop(d, None)
                        c._clean.pop(s, None)
                        for p in (s, d):
                            if p in c.session.caps:
                                c.mds.release_cap(c.session, p)
                    await self.cluster.rename(s, d)
                # purge EVERY client's caches under both trees (the
                # rename may have moved a whole subtree)
                for c in self._clients.values():
                    for cache in (c._dirty, c._clean):
                        for p in list(cache):
                            if _is_under(p, s) or _is_under(p, d):
                                cache.pop(p, None)
                return
            except FsError as e:
                if "EAGAIN" not in str(e) or attempt == retries - 1:
                    raise
                await self.renew_all()
                await asyncio.sleep(delay)

    # -- snapshots: every snap-table mutation routes through rank 0 (the
    # reference's snapserver runs on rank 0) UNDER AN ALL-RANKS BARRIER,
    # so no rank can decide a drop_old_ino against a table the snapshot
    # is about to change (and the walk is point-in-time, not fuzzy) ---------

    async def snap_create(self, path: str, name: str) -> None:
        p = _norm(path)
        # flush EVERY per-rank client's write-behind under the subtree
        # THROUGH THE ROUTER (handoff + frozen retry): bytes staged at a
        # stale authority must not be flushed through it
        for c in list(self._clients.values()):
            for dirty in list(c._dirty):
                if _is_under(dirty, p):
                    await self._routed(dirty, "fsync")
        await self.cluster.snap_create(p, name)

    async def snap_delete(self, path: str, name: str) -> None:
        await self.cluster.snap_delete(path, name)

    async def snap_list(self, path: str) -> List[str]:
        return await self.cluster.ranks[0].fs.snap_list(path)

    async def read_snap(self, path: str, name: str, rel: str) -> bytes:
        return await self.cluster.ranks[0].fs.read_snap_file(
            path, name, rel)

    async def listdir_snap(self, path: str, name: str,
                           rel: str = "") -> List[str]:
        return await self.cluster.ranks[0].fs.listdir_snap(
            path, name, rel)

    async def unmount(self) -> None:
        for c in self._clients.values():
            await c.unmount()
        self._clients.clear()
