"""RGW-lite: S3-style object gateway over RADOS.

Role-equivalent of the reference's RGW core request path (reference
src/rgw/): an asyncio HTTP frontend (the beast frontend role) maps
S3-shaped requests onto RADOS — buckets are index objects, object data is
striped over RADOS objects (rgw_max_chunk_size-style chunking via the
striper), and listings come from the bucket index, not pool scans, exactly
the reference's bucket-index discipline.

Multipart uploads (reference rgw_multipart): POST ?uploads opens an
upload, PUT ?uploadId=..&partNumber=N stores each part as its own striped
object, complete-POST records a MANIFEST in the bucket index (the
reference's RGWObjManifest role) that GET stitches back in part order —
parts are never rewritten into one blob.  Abort deletes the parts.

Auth (reference rgw_auth + AWS SigV4): when the service is constructed
with credentials, every request must carry an AWS4-HMAC-SHA256
Authorization header whose signature verifies over the canonical request
(method, path, signed headers, payload hash) with the standard SigV4
signing-key chain (date -> region -> service -> aws4_request).  Unsigned
requests get 403.  Without configured credentials the gateway stays open
(the reference's anonymous/system mode), so embedded uses need no keys.

API subset: PUT /b (create bucket), GET / (list buckets), PUT /b/k,
GET /b/k, DELETE /b/k (and bucket), GET /b (list objects), HEAD /b/k,
POST /b/k?uploads, PUT /b/k?uploadId&partNumber, POST /b/k?uploadId
(complete), DELETE /b/k?uploadId (abort) — plus the Swift dialect
(tempauth /auth/v1.0, /v1/AUTH_<acct>/container/object routes,
reference rgw_rest_swift.h).

Multisite (reference src/rgw/driver/rados/rgw_sync.cc): every mutation
appends to the zone's bounded data log; a ZoneSyncAgent replays another
zone's log resumably — full image sync (including deletions) when first
contacted or when trimmed past its position, incremental tail after.
Replicated applies suppress the destination's own datalog so
active-active pairs do not echo.

Data management (reference src/rgw/rgw_lc.cc, rgw_acl.cc): per-bucket
VERSIONING (every put appends a version; deletes add delete markers;
gets resolve the newest live version or an explicit versionId),
LIFECYCLE expiration rules swept by lifecycle_tick (prefix + age; the
mgr/embedder drives the tick, injectable clock), and bucket ACLs
(owner + grants, canned private/public-read) enforced by the HTTP
frontend's principal resolution.
"""

from __future__ import annotations

import asyncio
import contextvars
import errno
import hashlib
import hmac
import json
import re
import time
import uuid
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qsl, quote, unquote, urlsplit

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx
from ceph_tpu.rados.striper import RadosStriper

BUCKETS_ROOT = ".rgw.buckets"  # registry of buckets

# Task-scoped datalog suppression: set while a ZoneSyncAgent task APPLIES
# replicated mutations, so they do not re-enter the destination's datalog
# (active-active echo).  A contextvar — NOT a service attribute — so a
# concurrent local client mutation on the same gateway in another task
# still logs; a service-wide flag would silently skip its _log_mutation
# and leave a permanent replication gap.
_DATALOG_SUPPRESS: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "rgw_datalog_suppress", default=False)


class RgwService:
    """Bucket/object operations (usable directly or via the HTTP frontend)."""

    def __init__(self, ioctx: IoCtx, chunk_size: int = 1 << 20,
                 credentials: Optional[Dict[str, str]] = None):
        self.ioctx = ioctx
        self.striper = RadosStriper(ioctx, object_size=chunk_size)
        # access_key -> secret_key; empty = anonymous gateway.  The
        # ctor dict seeds static credentials; managed users (RgwAdmin)
        # are merged in by load_users().
        self.credentials = dict(credentials or {})
        self._static_credentials = dict(credentials or {})
        self.users: Dict[str, Dict] = {}  # uid -> user record
        self._users_loaded_at = 0.0
        # user-record staleness bound on a RUNNING gateway: admin
        # changes from another process (suspend, quota enable) take
        # effect within this window without a restart
        self.users_refresh_ttl = 2.0
        # usage figures are cached per principal/bucket for this long:
        # quota enforcement is deliberately approximate within the
        # window (the reference's RGWQuotaCache makes the same trade)
        self.usage_cache_ttl = 2.0
        self._usage_cache: Dict[str, Tuple[float, Dict[str, int]]] = {}
        self._bucket_usage_cache: Dict[str, Tuple[float,
                                                  Tuple[int, int]]] = {}
        self._owner_cache: Dict[str, Optional[str]] = {}  # bucket -> owner
        self._uploads_lock = asyncio.Lock()

    # -- users / quotas (reference rgw_user.cc, RGWQuotaHandler) -------------

    USERS_OID = ".rgw.users"

    async def load_users(self) -> None:
        """Load the persisted user store and rebuild the credential
        map (static ctor credentials + every active managed user)."""
        try:
            self.users = json.loads(await self.ioctx.read(self.USERS_OID))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            self.users = {}
        creds = dict(self._static_credentials)
        for u in self.users.values():
            creds[u["access_key"]] = u["secret_key"]
        self.credentials = creds
        self._users_loaded_at = time.monotonic()

    async def maybe_reload_users(self) -> None:
        """TTL-bounded refresh of the user store, so a live gateway
        honors out-of-process admin changes (suspend/quota) without a
        restart."""
        if time.monotonic() - self._users_loaded_at > self.users_refresh_ttl:
            await self.load_users()

    def user_by_access(self, access_key: Optional[str]) -> Optional[Dict]:
        if access_key is None:
            return None
        for u in self.users.values():
            if u.get("access_key") == access_key:
                return u
        return None

    def _invalidate_usage(self, bucket: str) -> None:
        """Drop the usage figures a mutation on `bucket` staled: the
        bucket's own entry, and the owning principal's aggregate (owner
        known from the meta-read cache; unknown owner falls back to a
        full clear, the safe direction)."""
        self._bucket_usage_cache.pop(bucket, None)
        owner = self._owner_cache.get(bucket)
        if owner is not None:
            self._usage_cache.pop(owner, None)
        else:
            self._usage_cache.clear()

    async def bucket_usage(self, bucket: str,
                           use_cache: bool = False) -> Tuple[int, int]:
        """(bytes, objects) currently indexed in the bucket — versions
        and multipart manifests count every stored generation, and
        STAGED multipart parts count toward bytes (or a capped user
        could park unbounded data in never-completed uploads)."""
        if use_cache:
            hit = self._bucket_usage_cache.get(bucket)
            if hit and time.monotonic() - hit[0] < self.usage_cache_ttl:
                return hit[1]
        index = await self._load_index(bucket)
        size = objects = 0
        for entry in (index or {}).values():
            if isinstance(entry, dict) and "versions" in entry:
                live = [v for v in entry["versions"]
                        if not v.get("delete_marker")]
                size += sum(int(v.get("size", 0)) for v in live)
                objects += 1 if live else 0
            elif isinstance(entry, dict):
                size += int(entry.get("size", 0))
                objects += 1
        for upload_id in await self._uploads_registry(bucket):
            try:
                up = await self._load_upload(bucket, upload_id)
            except RadosError:
                continue  # completed/aborted since the registry read
            size += sum(int(p.get("size", 0))
                        for p in up.get("parts", {}).values())
        self._bucket_usage_cache[bucket] = (time.monotonic(),
                                            (size, objects))
        while len(self._bucket_usage_cache) > 4096:
            self._bucket_usage_cache.pop(
                next(iter(self._bucket_usage_cache)))
        return size, objects

    async def usage(self, access_key: str,
                    use_cache: bool = False) -> Dict[str, int]:
        """Aggregate usage over every bucket the principal owns
        (radosgw-admin usage role)."""
        if use_cache:
            hit = self._usage_cache.get(access_key)
            if hit and time.monotonic() - hit[0] < self.usage_cache_ttl:
                return hit[1]
        total_size = total_objects = buckets = 0
        for bucket in await self.list_buckets(strict=True):
            meta = await self.get_bucket_meta(bucket)
            if meta.get("owner") != access_key:
                continue
            s, o = await self.bucket_usage(bucket, use_cache=use_cache)
            total_size += s
            total_objects += o
            buckets += 1
        out = {"size": total_size, "objects": total_objects,
               "buckets": buckets}
        self._usage_cache[access_key] = (time.monotonic(), out)
        while len(self._usage_cache) > 4096:
            self._usage_cache.pop(next(iter(self._usage_cache)))
        return out

    @staticmethod
    def _quota_violated(quota: Optional[Dict], size: int, objects: int,
                        add_bytes: int, add_objects: int) -> bool:
        if not quota or not quota.get("enabled"):
            return False
        max_size = int(quota.get("max_size", -1))
        max_objects = int(quota.get("max_objects", -1))
        if max_size >= 0 and size + add_bytes > max_size:
            return True
        if max_objects >= 0 and objects + add_objects > max_objects:
            return True
        return False

    async def check_quota(self, access_key: Optional[str], bucket: str,
                          add_bytes: int, add_objects: int = 1) -> None:
        """Raise QuotaExceeded (EDQUOT) if the write would break the
        principal's user quota or the bucket quota (reference
        RGWQuotaHandler::check_quota, consulted pre-exec)."""
        user = self.user_by_access(access_key)
        if user is None:
            return
        bq = user.get("bucket_quota")
        uq = user.get("quota")
        if bq and bq.get("enabled"):
            s, o = await self.bucket_usage(bucket, use_cache=True)
            if self._quota_violated(bq, s, o, add_bytes, add_objects):
                raise RadosError("QuotaExceeded: bucket quota",
                                 code=-errno.EDQUOT)
        if uq and uq.get("enabled"):
            u = await self.usage(access_key, use_cache=True)
            if self._quota_violated(uq, u["size"], u["objects"],
                                    add_bytes, add_objects):
                raise RadosError("QuotaExceeded: user quota",
                                 code=-errno.EDQUOT)

    @staticmethod
    def _index_oid(bucket: str) -> str:
        return f".bucket.index.{bucket}"

    # -- data log (multisite source side; reference datalog/bilog) ----------

    async def datalog_state(self) -> Dict:
        """One read: {"log": [...], "trimmed": floor} — callers must not
        stitch log and floor from two reads (a trim in between would
        force a spurious full re-sync)."""
        try:
            return json.loads(await self.ioctx.read(".rgw.datalog"))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            return {"log": [], "trimmed": 0}

    async def _log_mutation(self, op: str, bucket: str,
                            key: Optional[str] = None) -> None:
        """Append one mutation to the zone's data log (bounded: agents
        whose position predates the trim floor run a full re-sync).
        Serialized — the append is a read-modify-write of one object,
        and a lost entry is a silent replication gap.  Suppressed while
        a sync agent is APPLYING replicated mutations, so active-active
        topologies do not echo entries back and forth forever.  The
        whole-object rewrite is O(window) per mutation; the reference
        shards its datalog — acceptable at this gateway's scale, noted
        as the next step if the log becomes hot."""
        # any mutation invalidates the usage caches FIRST (before the
        # sync-agent suppression — replicated applies change usage too),
        # so this gateway's own quota checks never see their own writes
        # stale; cross-gateway writes are bounded by usage_cache_ttl
        self._invalidate_usage(bucket)
        if _DATALOG_SUPPRESS.get():
            return
        lock = getattr(self, "_datalog_lock", None)
        if lock is None:
            lock = self._datalog_lock = asyncio.Lock()
        async with lock:
            d = await self.datalog_state()
            seq = (d["log"][-1]["seq"] + 1) if d["log"] else d["trimmed"] + 1
            d["log"].append({"seq": seq, "op": op, "bucket": bucket,
                             "key": key})
            while len(d["log"]) > 4096:
                d["trimmed"] = d["log"].pop(0)["seq"]
            await self.ioctx.write_full(".rgw.datalog",
                                        json.dumps(d).encode())

    async def _load_index(self, bucket: str) -> Optional[Dict[str, Dict]]:
        try:
            return json.loads(await self.ioctx.read(self._index_oid(bucket)))
        except RadosError as e:
            # None means the bucket verifiably does not exist (-ENOENT).
            # A transient failure (-EAGAIN shard unavailability, timeout
            # exhaustion) must surface as an error — mapping it to None
            # would 404 a bucket that exists (NoSuchBucket vs 503).
            if e.code == -errno.ENOENT:
                return None
            raise

    async def _save_index(self, bucket: str, index: Dict[str, Dict]) -> None:
        await self.ioctx.write_full(self._index_oid(bucket),
                                    json.dumps(index).encode())

    async def _idx_cls(self, bucket: str, method: str, payload: Dict):
        """Bucket-index mutation as a single in-OSD class call
        (reference cls_rgw, src/cls/rgw/cls_rgw.cc: the index is
        cls-maintained precisely so concurrent gateways update it
        atomically).  Returns (ret, out), or None on an EC pool — where
        class calls answer EOPNOTSUPP per reference semantics — so
        callers fall back to the client-side read-modify-write (which is
        then the ONLY writer path and keeps its existing behavior)."""
        try:
            return await self.ioctx.execute(
                self._index_oid(bucket), "rgw", method,
                json.dumps(payload).encode())
        except RadosError as e:
            if e.code == -errno.EOPNOTSUPP:
                return None
            raise

    # -- bucket metadata: versioning / lifecycle / ACL ----------------------
    #
    # Stored beside the index (rare admin writes: client-side RMW is the
    # single-writer admin path, like the reference's bucket-info cache).

    @staticmethod
    def _meta_oid(bucket: str) -> str:
        return f".bucket.meta.{bucket}"

    async def get_bucket_meta(self, bucket: str) -> Dict:
        try:
            meta = json.loads(await self.ioctx.read(self._meta_oid(bucket)))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            meta = {"versioning": False, "lifecycle": [], "acl": None}
        self._owner_cache[bucket] = meta.get("owner")
        while len(self._owner_cache) > 8192:
            self._owner_cache.pop(next(iter(self._owner_cache)))
        return meta

    async def _save_bucket_meta(self, bucket: str, meta: Dict) -> None:
        await self.ioctx.write_full(self._meta_oid(bucket),
                                    json.dumps(meta).encode())
        self._owner_cache[bucket] = meta.get("owner")

    async def set_versioning(self, bucket: str, enabled: bool) -> None:
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}", code=-errno.ENOENT)
        meta = await self.get_bucket_meta(bucket)
        meta["versioning"] = bool(enabled)
        await self._save_bucket_meta(bucket, meta)

    async def put_lifecycle(self, bucket: str, rules: List[Dict]) -> None:
        """rules: [{"prefix": str, "days": N}, ...] — objects whose key
        matches prefix and whose age exceeds N days expire on the next
        lifecycle_tick (reference RGWLC rule model in miniature)."""
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}", code=-errno.ENOENT)
        for rule in rules:
            try:
                float(rule["days"])
            except (KeyError, TypeError, ValueError):
                raise RadosError("MalformedXML: lifecycle rule needs "
                                 "numeric days", code=-errno.EINVAL) from None
        meta = await self.get_bucket_meta(bucket)
        meta["lifecycle"] = list(rules)
        await self._save_bucket_meta(bucket, meta)

    async def put_bucket_acl(self, bucket: str, acl: Dict) -> None:
        """acl: {"owner": access_key, "grants": [{"grantee": "*"|key,
        "perm": "READ"|"WRITE"|"FULL_CONTROL"}]} (canned "private" =
        owner-only, "public-read" = owner + {"*": READ})."""
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}", code=-errno.ENOENT)
        meta = await self.get_bucket_meta(bucket)
        meta["acl"] = acl
        await self._save_bucket_meta(bucket, meta)

    # -- bucket policy (reference src/rgw/rgw_iam_policy.cc) ------------------

    async def put_bucket_policy(self, bucket: str, policy: Dict) -> None:
        """S3-style policy document: {"Version": ..., "Statement":
        [{"Effect": "Allow"|"Deny", "Principal": "*"|key|{"AWS": [...]},
        "Action": "s3:GetObject"|[...], "Resource": arn|[...]}]}.
        Statements support trailing-* wildcards in Action and Resource
        exactly like the reference's IAM matcher."""
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}", code=-errno.ENOENT)
        for stmt in policy.get("Statement", ()):
            if stmt.get("Effect") not in ("Allow", "Deny"):
                raise RadosError("MalformedPolicy: bad Effect",
                                 code=-errno.EINVAL)
        meta = await self.get_bucket_meta(bucket)
        meta["policy"] = policy
        await self._save_bucket_meta(bucket, meta)

    async def delete_bucket_policy(self, bucket: str) -> None:
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}", code=-errno.ENOENT)
        meta = await self.get_bucket_meta(bucket)
        meta["policy"] = None
        await self._save_bucket_meta(bucket, meta)

    @staticmethod
    def _iam_match(pattern: str, value: str) -> bool:
        if pattern.endswith("*"):
            return value.startswith(pattern[:-1])
        return pattern == value

    @staticmethod
    def policy_eval(policy: Optional[Dict], principal: Optional[str],
                    action: str, resource: str) -> Optional[str]:
        """Evaluate the bucket policy for (principal, action, resource):
        returns "Deny" (explicit deny — overrides everything), "Allow"
        (explicit allow), or None (no statement matched — the caller
        falls through to the ACL, the reference's PASS verdict)."""
        if not policy:
            return None
        verdict: Optional[str] = None
        for stmt in policy.get("Statement", ()):
            pr = stmt.get("Principal", "*")
            if isinstance(pr, dict):
                pr = pr.get("AWS", [])
            principals = [pr] if isinstance(pr, str) else list(pr)
            if "*" not in principals and principal not in principals:
                continue
            actions = stmt.get("Action", [])
            if isinstance(actions, str):
                actions = [actions]
            if not any(RgwService._iam_match(a, action) for a in actions):
                continue
            resources = stmt.get("Resource", [])
            if isinstance(resources, str):
                resources = [resources]
            if resources and not any(RgwService._iam_match(r, resource)
                                     for r in resources):
                continue
            if stmt.get("Effect") == "Deny":
                return "Deny"  # deny-overrides: stop immediately
            verdict = "Allow"
        return verdict

    @staticmethod
    def acl_allows(acl: Optional[Dict], principal: Optional[str],
                   need: str) -> bool:
        """ACL check (reference rgw_acl verify_permission): no ACL set =
        open (the gateway's anonymous/system mode keeps working); the
        owner may do anything; grants match an explicit principal or
        the public grantee "*"."""
        if not acl:
            return True
        if principal is not None and acl.get("owner") == principal:
            return True
        for g in acl.get("grants", ()):
            if g.get("grantee") not in ("*", principal):
                continue
            perm = g.get("perm", "")
            if perm == "FULL_CONTROL" or perm == need:
                return True
        return False

    async def lifecycle_tick(self, now: Optional[float] = None) -> int:
        """One expiration sweep over every bucket's lifecycle rules
        (reference RGWLC::process): expired objects are deleted through
        the normal path (so versioned buckets get delete markers and the
        datalog replicates the expiry).  The mgr/embedder drives this on
        its periodic tick; `now` is injectable for tests.  Returns the
        number of objects expired."""
        now = time.time() if now is None else now
        expired = 0
        for bucket in list(await self.list_buckets()):
            try:
                expired += await self._lifecycle_bucket(bucket, now)
            except Exception:
                # one bucket's bad state must not stop the cluster-wide
                # sweep (reference RGWLC isolates per-bucket failures)
                continue
        return expired

    async def _lifecycle_bucket(self, bucket: str, now: float) -> int:
        meta = await self.get_bucket_meta(bucket)
        rules = meta.get("lifecycle") or []
        if not rules:
            return 0
        index = await self._load_index(bucket)
        if not index:
            return 0
        expired = 0
        for key, entry in list(index.items()):
            ts = entry.get("ts")
            if "versions" in entry:
                vs = entry["versions"]
                if not vs or vs[-1].get("delete_marker"):
                    continue  # already expired/deleted
                ts = vs[-1].get("ts")
            if ts is None:
                # unknown age (pre-versioning or multipart entries
                # without a stamp) must NEVER expire — deleting data of
                # unknown age is silent loss, not lifecycle policy
                continue
            for rule in rules:
                if not key.startswith(rule.get("prefix", "")):
                    continue
                try:
                    days = float(rule["days"])
                except (KeyError, TypeError, ValueError):
                    continue  # malformed legacy rule: skip, not crash
                if now - ts >= days * 86400.0:
                    try:
                        await self.delete_object(bucket, key, now=now)
                        expired += 1
                    except RadosError:
                        pass
                    break
        return expired

    async def create_bucket(self, bucket: str,
                            owner: Optional[str] = None) -> None:
        created = False
        made = await self._idx_cls(bucket, "bucket_init", {})
        if made is not None:
            ret, _ = made
            if ret not in (0, -17):  # -EEXIST: already created, idempotent
                raise RadosError(f"bucket_init failed ({ret})", code=ret)
            if ret == 0:
                created = True
                try:
                    await self.ioctx.execute(
                        BUCKETS_ROOT, "rgw", "registry_add",
                        json.dumps({"bucket": bucket}).encode())
                except RadosError as e:
                    if e.code != -errno.EOPNOTSUPP:
                        raise
                await self._log_mutation("create_bucket", bucket)
        elif await self._load_index(bucket) is None:
            created = True
            await self._save_index(bucket, {})
            buckets = await self.list_buckets()
            if bucket not in buckets:
                buckets.append(bucket)
                await self.ioctx.write_full(
                    BUCKETS_ROOT, json.dumps(sorted(buckets)).encode())
            await self._log_mutation("create_bucket", bucket)
        if created and owner is not None:
            # bucket ownership (reference rgw_bucket owner field): the
            # creating principal's uid keys quota/usage accounting
            meta = await self.get_bucket_meta(bucket)
            meta["owner"] = owner
            await self._save_bucket_meta(bucket, meta)

    async def list_buckets(self, strict: bool = False) -> List[str]:
        """strict=True re-raises transient read failures instead of
        answering [] — quota enforcement must fail CLOSED, not admit
        writes because the registry was momentarily unreadable."""
        try:
            return json.loads(await self.ioctx.read(BUCKETS_ROOT))
        except RadosError as e:
            if strict and e.code != -errno.ENOENT:
                raise
            return []

    async def _drop_parts(self, entry: Dict) -> None:
        """Remove ONLY a manifest entry's part objects — never the plain
        striped object, which after a multipart->plain replace holds the
        bytes that were JUST written."""
        for p in entry.get("parts", ()):
            try:
                await self.striper.remove(p["oid"])
            except RadosError:
                pass

    async def put_object(self, bucket: str, key: str, data: bytes,
                         now: Optional[float] = None,
                         bmeta: Optional[Dict] = None) -> Optional[str]:
        # existence check BEFORE writing data: a put to a missing bucket
        # must not orphan striped objects (small TOCTOU window against a
        # concurrent bucket delete is bounded and matches the reference)
        index0 = await self._load_index(bucket)
        if index0 is None:
            raise RadosError(f"NoSuchBucket: {bucket}", code=-errno.ENOENT)
        now = time.time() if now is None else now
        if bmeta is None:
            bmeta = await self.get_bucket_meta(bucket)
        entry0 = index0.get(key)
        if bmeta.get("versioning") or (
                isinstance(entry0, dict) and "versions" in entry0):
            # versioned bucket — or a SUSPENDED bucket whose key already
            # has a version stack: history must survive suspension
            # (divergence: suspended puts append a fresh vid rather than
            # replacing the "null" version)
            return await self._put_versioned(bucket, key, data, now)
        meta = {"size": len(data), "etag": hashlib.md5(data).hexdigest(),
                "ts": now}
        await self.striper.write(f"{bucket}/{key}", data)
        got = await self._idx_cls(bucket, "index_put",
                                  {"key": key, "meta": meta})
        if got is not None:
            ret, out = got
            if ret == -2:
                raise RadosError(f"NoSuchBucket: {bucket}",
                                 code=-errno.ENOENT)
            if ret < 0:
                raise RadosError(f"index_put failed ({ret})", code=ret)
            prev = json.loads(out or b"{}").get("prev")
            if prev and "parts" in prev:
                # the replaced entry was a multipart manifest: its part
                # objects are unreferenced now (parts ONLY — the plain
                # striped object is the data just written)
                await self._drop_parts(prev)
            await self._log_mutation("put", bucket, key)
            return None
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        prev = index.get(key)
        index[key] = meta
        await self._save_index(bucket, index)
        if prev and "parts" in prev:
            await self._drop_parts(prev)
        await self._log_mutation("put", bucket, key)
        return None

    @staticmethod
    def _version_oid(bucket: str, key: str, vid: str) -> str:
        return f"{bucket}/{key}@{vid}"

    @staticmethod
    def _as_versioned_entry(entry: Optional[Dict]) -> Dict:
        """Flat index entry -> versioned form (the existing state becomes
        the addressable "null" version, as S3 does on enabling
        versioning)."""
        if isinstance(entry, dict) and "versions" in entry:
            return entry
        return {"versions": ([] if entry is None else
                             [dict(entry, vid="null",
                                   ts=entry.get("ts", 0))])}

    @staticmethod
    def _set_derived(entry: Dict) -> Dict:
        """Size/etag follow the CURRENT (newest) version; a delete-marker
        current means the flat view reads empty.  One rule, shared with
        the in-OSD class methods."""
        cur = entry["versions"][-1] if entry["versions"] else None
        if cur is not None and cur.get("delete_marker"):
            cur = None
        entry["size"] = cur.get("size", 0) if cur else 0
        entry["etag"] = cur.get("etag", "") if cur else ""
        return entry

    async def _put_versioned(self, bucket: str, key: str, data: bytes,
                             now: float) -> str:
        """Versioned PUT (reference versioned-bucket semantics): every
        put appends a NEW version; nothing is overwritten."""
        vid = uuid.uuid4().hex[:16]
        await self.striper.write(self._version_oid(bucket, key, vid), data)
        ver = {"vid": vid, "size": len(data),
               "etag": hashlib.md5(data).hexdigest(), "ts": now}
        got = await self._idx_cls(bucket, "index_put_version",
                                  {"key": key, "version": ver})
        if got is not None:
            ret, _ = got
            if ret == -2:
                raise RadosError(f"NoSuchBucket: {bucket}",
                                 code=-errno.ENOENT)
            if ret < 0:
                raise RadosError(f"index_put_version failed ({ret})",
                                 code=ret)
        else:
            index = await self._load_index(bucket)
            if index is None:
                raise RadosError(f"NoSuchBucket: {bucket}")
            entry = self._as_versioned_entry(index.get(key))
            entry["versions"].append(ver)
            index[key] = self._set_derived(entry)
            await self._save_index(bucket, index)
        await self._log_mutation("put", bucket, key)
        return vid

    async def _resolve_object(self, bucket: str, key: str,
                              version_id: Optional[str] = None):
        """One resolution of (bucket, key[, version]) to its storage
        form: ("plain", soid, size) or ("manifest", parts, size) — the
        shared head of full GET, Range GET, and CopyObject (reference
        RGWObjManifest resolution in RGWGetObj/RGWCopyObj)."""
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        if key not in index:
            raise RadosError(f"NoSuchKey: {key}")
        entry = index[key]
        if "versions" in entry:
            versions = entry["versions"]
            if version_id is not None:
                match = [v for v in versions if v.get("vid") == version_id]
                if not match:
                    raise RadosError(f"NoSuchVersion: {version_id}",
                                     code=-errno.ENOENT)
                v = match[0]
                if v.get("delete_marker"):
                    raise RadosError(f"MethodNotAllowed: {version_id} is "
                                     f"a delete marker")
            else:
                if not versions or versions[-1].get("delete_marker"):
                    # the CURRENT (newest) version is a delete marker:
                    # the object reads as absent (S3 semantics)
                    raise RadosError(f"NoSuchKey: {key}",
                                     code=-errno.ENOENT)
                v = versions[-1]
            if "parts" in v:
                return ("manifest", v["parts"],
                        sum(p["size"] for p in v["parts"]))
            if v.get("vid") == "null":
                return ("plain", f"{bucket}/{key}", v.get("size", 0))
            return ("plain", self._version_oid(bucket, key, v["vid"]),
                    v.get("size", 0))
        if "parts" in entry:
            return ("manifest", entry["parts"],
                    sum(p["size"] for p in entry["parts"]))
        return ("plain", f"{bucket}/{key}", entry.get("size", 0))

    async def _read_resolved(self, kind: str, ref) -> bytes:
        if kind == "manifest":
            # manifest object: stitch the parts in order (RGWObjManifest)
            blobs = await asyncio.gather(
                *(self.striper.read(p["oid"]) for p in ref))
            return b"".join(blobs)
        return await self.striper.read(ref)

    async def get_object(self, bucket: str, key: str,
                         version_id: Optional[str] = None) -> bytes:
        kind, ref, _size = await self._resolve_object(bucket, key,
                                                      version_id)
        return await self._read_resolved(kind, ref)

    @staticmethod
    def parse_range(spec: str, total: int) -> Optional[Tuple[int, int]]:
        """RFC 7233 single byte-range (reference RGWGetObj range
        parsing): 'bytes=a-b' / 'bytes=a-' / 'bytes=-N' -> (start,
        end_inclusive) clamped to `total`.  Returns None for a
        malformed spec (S3: ignore the header, serve the whole
        object); raises InvalidRange (-ERANGE) when syntactically
        valid but unsatisfiable — the 416 contract."""
        m = re.fullmatch(r"bytes=(\d*)-(\d*)", spec.strip())
        if not m or (not m.group(1) and not m.group(2)):
            return None
        a, b = m.group(1), m.group(2)
        if a and b and int(b) < int(a):
            # RFC 7233 §2.1: last-byte-pos < first-byte-pos makes the
            # spec syntactically INVALID — ignored, not 416
            return None
        if not a:  # suffix form: last N bytes
            n = int(b)
            if n == 0 or total == 0:
                raise RadosError("InvalidRange", code=-errno.ERANGE)
            start, end = max(0, total - n), total - 1
        else:
            start = int(a)
            if start >= total:
                raise RadosError("InvalidRange", code=-errno.ERANGE)
            end = min(int(b), total - 1) if b else total - 1
        return (start, end)

    async def get_object_range(self, bucket: str, key: str, spec: str,
                               version_id: Optional[str] = None
                               ) -> Tuple[bytes, int,
                                          Optional[Tuple[int, int]]]:
        """Range GET (reference RGWGetObj with ofs/end): only the
        stripes/parts overlapping the range are read.  Returns
        (bytes, total_size, (start, end_inclusive)); a malformed spec
        degrades to the full object per S3."""
        kind, ref, total = await self._resolve_object(bucket, key,
                                                      version_id)
        try:
            rng = self.parse_range(spec, total)
        except RadosError as e:
            # unsatisfiable: carry the total so the 416 reply's
            # Content-Range needs no second resolution
            e.total = total
            raise
        if rng is None:
            # malformed spec: serve the whole object (S3 ignores the
            # header); rng=None tells the frontend to answer 200 —
            # read the form already resolved, no second index read
            return await self._read_resolved(kind, ref), total, None
        start, end = rng
        length = end - start + 1
        if kind == "plain":
            return (await self.striper.read_range(ref, start, length),
                    total, rng)
        # manifest: walk parts by cumulative offset, partial-read only
        # the overlapping ones (the multipart analog of the stripe walk)
        chunks, pos = [], 0
        for p in ref:
            p_end = pos + p["size"]
            if p_end > start and pos <= end:
                sub_off = max(0, start - pos)
                sub_len = min(end + 1, p_end) - (pos + sub_off)
                chunks.append(self.striper.read_range(
                    p["oid"], sub_off, sub_len))
            pos = p_end
            if pos > end:
                break
        return b"".join(await asyncio.gather(*chunks)), total, rng

    async def stat_object(self, bucket: str, key: str,
                          version_id: Optional[str] = None) -> int:
        """Total size without reading data (HEAD / 416 support)."""
        _kind, _ref, total = await self._resolve_object(bucket, key,
                                                        version_id)
        return total

    async def copy_object(self, src_bucket: str, src_key: str,
                          dst_bucket: str, dst_key: str,
                          version_id: Optional[str] = None,
                          principal: Optional[str] = None) -> Dict:
        """Server-side copy (reference RGWCopyObj, x-amz-copy-source):
        the data never leaves the cluster — read source form, write
        destination through the normal put path (index + versioning +
        datalog all apply).  Tags copy with the object (S3 default
        COPY directive)."""
        data = await self.get_object(src_bucket, src_key,
                                     version_id=version_id)
        # read source tags BEFORE the destination put: copying an
        # object onto itself replaces the index entry, and reading
        # after would see the fresh (tagless) entry and drop them
        src_index = await self._load_index(src_bucket)
        tags = (src_index or {}).get(src_key, {}).get("tags")
        await self.check_quota(principal, dst_bucket, len(data))
        vid = await self.put_object(dst_bucket, dst_key, data)
        if tags and version_id is None:
            await self.put_object_tagging(dst_bucket, dst_key, tags)
        out = {"ETag": hashlib.md5(data).hexdigest(),
               "LastModified": time.time()}
        if vid:
            out["VersionId"] = vid
        return out

    # -- object tagging (reference rgw_tag.cc, cls_rgw: tags ride the
    #    bucket index entry, not the object data) ---------------------------

    async def put_object_tagging(self, bucket: str, key: str,
                                 tags: Dict[str, str]) -> None:
        if not isinstance(tags, dict) or len(tags) > 10:
            raise RadosError("InvalidTag: at most 10 tags",
                             code=-errno.EINVAL)
        await self._set_tags(bucket, key, dict(tags))

    async def get_object_tagging(self, bucket: str, key: str
                                 ) -> Dict[str, str]:
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        if key not in index:
            raise RadosError(f"NoSuchKey: {key}")
        return dict(index[key].get("tags") or {})

    async def delete_object_tagging(self, bucket: str, key: str) -> None:
        await self._set_tags(bucket, key, None)

    async def _set_tags(self, bucket: str, key: str,
                        tags: Optional[Dict[str, str]]) -> None:
        got = await self._idx_cls(bucket, "index_set_tags",
                                  {"key": key, "tags": tags})
        if got is not None:
            ret, _ = got
            if ret == -errno.ENOENT:
                raise RadosError(f"NoSuchKey: {key}", code=ret)
            if ret < 0:
                raise RadosError(f"index_set_tags failed ({ret})",
                                 code=ret)
            return
        # EC pool: client-side RMW is the single writer path
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        if key not in index:
            raise RadosError(f"NoSuchKey: {key}")
        if tags is None:
            index[key].pop("tags", None)
        else:
            index[key]["tags"] = tags
        await self._save_index(bucket, index)

    async def _drop_object_data(self, bucket: str, key: str,
                                entry: Optional[Dict]) -> None:
        """Remove an index entry's backing data in EVERY form it may
        exist: version objects (bucket/key@vid), manifest parts, and the
        plain striped object — a key may have been written all three
        ways over its lifetime, and dropping one form must not orphan
        another."""
        for v in (entry or {}).get("versions", ()):
            if v.get("delete_marker"):
                continue
            for p in v.get("parts", ()):
                try:
                    await self.striper.remove(p["oid"])
                except RadosError:
                    pass
            if v.get("vid") not in (None, "null"):
                try:
                    await self.striper.remove(
                        self._version_oid(bucket, key, v["vid"]))
                except RadosError:
                    pass
        if entry and "parts" in entry:
            for p in entry["parts"]:
                try:
                    await self.striper.remove(p["oid"])
                except RadosError:
                    pass
        try:
            await self.striper.remove(f"{bucket}/{key}")
        except RadosError:
            pass

    async def delete_object(self, bucket: str, key: str,
                            version_id: Optional[str] = None,
                            now: Optional[float] = None,
                            bmeta: Optional[Dict] = None) -> None:
        now = time.time() if now is None else now
        if bmeta is None:
            bmeta = await self.get_bucket_meta(bucket)
        if version_id is not None:
            return await self._delete_version(bucket, key, version_id)
        versioned = bmeta.get("versioning")
        if not versioned:
            index0 = await self._load_index(bucket)
            entry0 = (index0 or {}).get(key)
            versioned = isinstance(entry0, dict) and "versions" in entry0
        if versioned:
            # versioned delete: a DELETE MARKER becomes the newest
            # version; data stays reachable via explicit versionIds
            marker = {"vid": uuid.uuid4().hex[:16], "delete_marker": True,
                      "ts": now}
            got = await self._idx_cls(bucket, "index_put_version",
                                      {"key": key, "version": marker})
            if got is None:
                index = await self._load_index(bucket)
                if index is None:
                    raise RadosError(f"NoSuchBucket: {bucket}")
                entry = self._as_versioned_entry(index.get(key))
                entry["versions"].append(marker)
                index[key] = self._set_derived(entry)
                await self._save_index(bucket, index)
            elif got[0] == -2:
                raise RadosError(f"NoSuchBucket: {bucket}",
                                 code=-errno.ENOENT)
            await self._log_mutation("delete", bucket, key)
            return
        got = await self._idx_cls(bucket, "index_rm", {"key": key})
        if got is not None:
            ret, out = got
            if ret == -2 and await self._load_index(bucket) is None:
                raise RadosError(f"NoSuchBucket: {bucket}",
                                 code=-errno.ENOENT)
            entry = (json.loads(out or b"{}").get("prev")
                     if ret == 0 else None)
            await self._drop_object_data(bucket, key, entry)
            await self._log_mutation("delete", bucket, key)
            return
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        entry = index.pop(key, None)
        await self._drop_object_data(bucket, key, entry)
        await self._save_index(bucket, index)
        await self._log_mutation("delete", bucket, key)

    async def _delete_version(self, bucket: str, key: str,
                              vid: str) -> None:
        """Permanently remove ONE version (S3 DELETE ?versionId=...)."""
        got = await self._idx_cls(bucket, "index_rm_version",
                                  {"key": key, "vid": vid})
        removed = None
        if got is not None:
            ret, out = got
            if ret == -2:
                raise RadosError(f"NoSuchVersion: {vid}",
                                 code=-errno.ENOENT)
            if ret < 0:
                raise RadosError(f"index_rm_version failed ({ret})",
                                 code=ret)
            removed = json.loads(out or b"{}").get("removed")
        else:
            index = await self._load_index(bucket)
            if index is None:
                raise RadosError(f"NoSuchBucket: {bucket}")
            entry = index.get(key)
            if not entry or "versions" not in entry:
                raise RadosError(f"NoSuchVersion: {vid}",
                                 code=-errno.ENOENT)
            match = [v for v in entry["versions"] if v.get("vid") == vid]
            if not match:
                raise RadosError(f"NoSuchVersion: {vid}",
                                 code=-errno.ENOENT)
            removed = match[0]
            entry["versions"] = [v for v in entry["versions"]
                                 if v.get("vid") != vid]
            if entry["versions"]:
                index[key] = self._set_derived(entry)
            else:
                index.pop(key)
            await self._save_index(bucket, index)
        if removed and not removed.get("delete_marker"):
            for p in removed.get("parts", ()):
                try:
                    await self.striper.remove(p["oid"])
                except RadosError:
                    pass
            if "parts" not in removed:
                oid = (f"{bucket}/{key}" if removed.get("vid") == "null"
                       else self._version_oid(bucket, key, vid))
                try:
                    await self.striper.remove(oid)
                except RadosError:
                    pass
        # a version-targeted delete changes the key's CURRENT state in a
        # direction only the source knows (prune, or undelete by marker
        # removal): replicas RESYNC the key instead of blindly deleting
        await self._log_mutation("resync", bucket, key)

    async def list_object_versions(self, bucket: str,
                                   key: Optional[str] = None) -> Dict:
        """{key: [versions newest-last]} (S3 ListObjectVersions role)."""
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        out: Dict[str, List[Dict]] = {}
        for k, entry in index.items():
            if key is not None and k != key:
                continue
            if "versions" in entry:
                out[k] = list(entry["versions"])
            else:
                out[k] = [dict(entry, vid="null")]
        return out

    async def list_objects(self, bucket: str) -> Dict[str, Dict]:
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        out = {}
        for k, entry in index.items():
            if "versions" in entry:
                vs = entry["versions"]
                if not vs or vs[-1].get("delete_marker"):
                    continue  # current version is a delete marker: hidden
            out[k] = entry
        return out

    async def delete_bucket(self, bucket: str) -> None:
        """Delete an EMPTY bucket (both S3 and Swift refuse non-empty
        deletion: BucketNotEmpty / 409 Conflict)."""
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        if index:
            raise RadosError(f"BucketNotEmpty: {bucket}")
        uploads = [o for o in await self.ioctx.list_objects()
                   if o.startswith(".upload.")
                   and o.rsplit(".", 1)[0] == f".upload.{bucket}"]
        if uploads:
            # the reference refuses deletion while multipart uploads are
            # in flight; allowing it would orphan every part object
            raise RadosError(f"BucketNotEmpty: {bucket} has "
                             f"{len(uploads)} multipart upload(s) in flight")
        await self.ioctx.remove(self._index_oid(bucket))
        try:
            # the bucket's versioning/lifecycle/ACL die with it — a
            # recreated bucket must not resurrect the old owner's policy
            await self.ioctx.remove(self._meta_oid(bucket))
        except RadosError:
            pass
        try:
            await self.ioctx.execute(
                BUCKETS_ROOT, "rgw", "registry_rm",
                json.dumps({"bucket": bucket}).encode())
        except RadosError as e:
            if e.code != -errno.EOPNOTSUPP:
                raise
            # EC pool: client-side registry (single-writer semantics)
            buckets = await self.list_buckets()
            if bucket in buckets:
                buckets.remove(bucket)
                await self.ioctx.write_full(
                    BUCKETS_ROOT, json.dumps(sorted(buckets)).encode())
        await self._log_mutation("delete_bucket", bucket)

    # -- multipart (reference rgw multipart upload machinery) ---------------

    @staticmethod
    def _upload_meta_oid(bucket: str, upload_id: str) -> str:
        return f".upload.{bucket}.{upload_id}"

    def _part_oid(self, bucket: str, upload_id: str, part: int) -> str:
        return f"_mp.{bucket}.{upload_id}.{part:05d}"

    @staticmethod
    def _uploads_oid(bucket: str) -> str:
        return f".uploads.{bucket}"

    async def _uploads_registry(self, bucket: str) -> List[str]:
        """Fail-closed like list_buckets(strict=True): quota accounting
        consumes this, so a transient read error must propagate rather
        than under-count staged bytes."""
        try:
            return json.loads(await self.ioctx.read(
                self._uploads_oid(bucket)))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            return []

    async def _uploads_registry_update(self, bucket: str, add=None,
                                       remove=None) -> None:
        # serialized read-modify-write (same discipline as
        # _log_mutation): a lost registry entry is staged bytes the
        # quota can never see again
        async with self._uploads_lock:
            ids = await self._uploads_registry(bucket)
            if add is not None and add not in ids:
                ids.append(add)
            if remove is not None and remove in ids:
                ids.remove(remove)
            await self.ioctx.write_full(self._uploads_oid(bucket),
                                        json.dumps(ids).encode())

    async def initiate_multipart(self, bucket: str, key: str) -> str:
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        upload_id = uuid.uuid4().hex
        await self.ioctx.write_full(
            self._upload_meta_oid(bucket, upload_id),
            json.dumps({"key": key, "parts": {}}).encode())
        # in-progress registry: staged parts are visible to usage
        # accounting (reference: uploads live in the bucket index's
        # multipart namespace and are listable/chargeable)
        await self._uploads_registry_update(bucket, add=upload_id)
        return upload_id

    async def list_multipart_uploads(self, bucket: str) -> List[Dict]:
        """In-progress uploads (reference RGWListBucketMultiparts, GET
        /bucket?uploads): upload id + target key per entry."""
        if await self._load_index(bucket) is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        out = []
        for upload_id in await self._uploads_registry(bucket):
            try:
                meta = await self._load_upload(bucket, upload_id)
            except RadosError as e:
                if e.code == -errno.ENOENT:
                    continue  # registry/meta race: entry mid-abort
                raise  # transient I/O error: fail closed, never omit
            out.append({"UploadId": upload_id, "Key": meta["key"]})
        return out

    async def list_parts(self, bucket: str, upload_id: str,
                         key: Optional[str] = None) -> List[Dict]:
        """Staged parts of one upload (reference RGWListMultipart, GET
        /bucket/key?uploadId): number, size, etag — what a resuming
        client needs to skip already-staged parts.  When `key` is
        given it must match the upload's target (the frontend's
        per-object authorization gate was evaluated against IT —
        a mismatch is NoSuchUpload, as S3 answers)."""
        meta = await self._load_upload(bucket, upload_id)
        if key is not None and meta["key"] != key:
            raise RadosError(f"NoSuchUpload: {upload_id} targets a "
                             "different key", code=-errno.ENOENT)
        return [{"PartNumber": int(n), "Size": p["size"],
                 "ETag": p["etag"]}
                for n, p in sorted(meta["parts"].items(),
                                   key=lambda kv: int(kv[0]))]

    async def _load_upload(self, bucket: str, upload_id: str) -> Dict:
        try:
            return json.loads(await self.ioctx.read(
                self._upload_meta_oid(bucket, upload_id)))
        except RadosError as e:
            if e.code == -errno.ENOENT:
                raise RadosError(f"NoSuchUpload: {upload_id}",
                                 code=-errno.ENOENT)
            raise  # transient I/O: keep the typed code, fail closed

    async def upload_part(self, bucket: str, upload_id: str, part: int,
                          data: bytes) -> str:
        meta = await self._load_upload(bucket, upload_id)
        oid = self._part_oid(bucket, upload_id, part)
        await self.striper.write(oid, data)
        etag = hashlib.md5(data).hexdigest()
        meta["parts"][str(part)] = {"oid": oid, "size": len(data),
                                    "etag": etag}
        await self.ioctx.write_full(
            self._upload_meta_oid(bucket, upload_id),
            json.dumps(meta).encode())
        # staged bytes count toward usage: the next part's quota check
        # must see this one
        self._invalidate_usage(bucket)
        return etag

    async def complete_multipart(self, bucket: str, upload_id: str,
                                 parts: Optional[List[int]] = None,
                                 principal: Optional[str] = None) -> str:
        """Assemble the object from its parts; the bucket index entry
        becomes a manifest referencing the part objects in order.
        Byte quota was charged when each part was STAGED (staged parts
        count in bucket_usage), but completion creates a NEW indexed
        object — the object-count axis must be re-checked here or
        multipart becomes a max_objects bypass (parts stage with
        add_objects=0; reference re-checks quota at completion)."""
        meta = await self._load_upload(bucket, upload_id)
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        # overwrite of an existing key replaces its index entry — the
        # object count only grows when the key is new (the index is in
        # hand here, so be exact where the plain-PUT pre-check is
        # conservative)
        await self.check_quota(
            principal, bucket, 0,
            add_objects=0 if meta["key"] in index else 1)
        have = {int(n): p for n, p in meta["parts"].items()}
        order = sorted(have) if parts is None else list(parts)
        if not order or any(n not in have for n in order):
            raise RadosError("InvalidPart: upload has missing parts")
        key = meta["key"]
        manifest = [have[n] for n in order]

        async def discard_unselected():
            # parts NOT selected into the manifest are discarded (S3
            # semantics) — leaving them stored after the upload's
            # registry entry vanished would be bytes no quota ever
            # counts again.  Runs AFTER the index commit: deleting them
            # first would let a failed commit + retried complete build a
            # manifest referencing already-deleted part objects.
            for n, p in have.items():
                if n not in order:
                    try:
                        await self.striper.remove(p["oid"])
                    except RadosError:
                        pass
        # S3 multipart etag convention: md5 of concatenated part md5s
        etag = hashlib.md5(
            b"".join(bytes.fromhex(p["etag"]) for p in manifest)
        ).hexdigest() + f"-{len(manifest)}"
        entry = {"size": sum(p["size"] for p in manifest),
                 "etag": etag, "parts": manifest, "ts": time.time()}
        bmeta = await self.get_bucket_meta(bucket)
        if bmeta.get("versioning") or (
                isinstance(index.get(key), dict)
                and "versions" in index[key]):
            # versioned bucket: multipart completion appends a VERSION
            # carrying its manifest — prior versions' data survives
            ver = dict(entry, vid=uuid.uuid4().hex[:16])
            got = await self._idx_cls(bucket, "index_put_version",
                                      {"key": key, "version": ver})
            if got is not None:
                ret, _ = got
                if ret < 0:
                    raise RadosError(f"index_put_version failed ({ret})",
                                     code=ret)
            else:
                e = self._as_versioned_entry(index.get(key))
                e["versions"].append(ver)
                index[key] = self._set_derived(e)
                await self._save_index(bucket, index)
            await discard_unselected()
            await self.ioctx.remove(self._upload_meta_oid(bucket, upload_id))
            await self._uploads_registry_update(bucket, remove=upload_id)
            await self._log_mutation("put", bucket, key)
            return etag
        got = await self._idx_cls(bucket, "index_put",
                                  {"key": key, "meta": entry})
        if got is not None:
            ret, out = got
            if ret < 0:
                raise RadosError(f"index_put failed ({ret})", code=ret)
            # the REPLACED entry's data is stale now: old parts and the
            # old plain object both (the new bytes live in OUR parts)
            prev = json.loads(out or b"{}").get("prev")
            await self._drop_object_data(bucket, key, prev)
        else:
            prev = index.get(key)
            index[key] = entry
            await self._save_index(bucket, index)
            await self._drop_object_data(bucket, key, prev)
        await discard_unselected()
        await self.ioctx.remove(self._upload_meta_oid(bucket, upload_id))
        await self._uploads_registry_update(bucket, remove=upload_id)
        # a completed multipart IS an object mutation: without this the
        # zone sync agent never replicates multipart uploads (and its
        # first act invalidates the usage caches)
        await self._log_mutation("put", bucket, key)
        return etag

    async def abort_multipart(self, bucket: str, upload_id: str) -> None:
        meta = await self._load_upload(bucket, upload_id)
        for p in meta["parts"].values():
            try:
                await self.striper.remove(p["oid"])
            except RadosError:
                pass
        await self.ioctx.remove(self._upload_meta_oid(bucket, upload_id))
        await self._uploads_registry_update(bucket, remove=upload_id)
        self._invalidate_usage(bucket)


# -- SigV4 (reference rgw_auth; AWS Signature Version 4) --------------------


class RgwAdmin:
    """radosgw-admin role (reference src/rgw/rgw_admin.cc, rgw_user.cc):
    managed-user lifecycle, quotas, and usage over a gateway's user
    store.  Users persist in the pool, so a restarted gateway serves
    the same principals."""

    def __init__(self, service: RgwService):
        self.service = service

    async def _load(self) -> Dict[str, Dict]:
        await self.service.load_users()
        return self.service.users

    async def _save(self, users: Dict[str, Dict]) -> None:
        await self.service.ioctx.write_full(
            self.service.USERS_OID, json.dumps(users).encode())
        await self.service.load_users()

    async def user_create(self, uid: str, display_name: str = "",
                          access_key: Optional[str] = None,
                          secret_key: Optional[str] = None) -> Dict:
        users = await self._load()
        if uid in users:
            raise RadosError(f"UserAlreadyExists: {uid}",
                             code=-errno.EEXIST)
        user = {
            "uid": uid,
            "display_name": display_name or uid,
            "access_key": access_key or uuid.uuid4().hex[:20].upper(),
            "secret_key": secret_key or uuid.uuid4().hex,
            "suspended": False,
            "quota": None,          # user-scope quota
            "bucket_quota": None,   # per-bucket quota
        }
        users[uid] = user
        await self._save(users)
        return dict(user)

    async def user_rm(self, uid: str) -> None:
        users = await self._load()
        if uid not in users:
            raise RadosError(f"NoSuchUser: {uid}", code=-errno.ENOENT)
        del users[uid]
        await self._save(users)

    async def user_info(self, uid: str) -> Dict:
        users = await self._load()
        if uid not in users:
            raise RadosError(f"NoSuchUser: {uid}", code=-errno.ENOENT)
        return dict(users[uid])

    async def user_list(self) -> List[str]:
        return sorted(await self._load())

    async def _set_suspended(self, uid: str, suspended: bool) -> None:
        users = await self._load()
        if uid not in users:
            raise RadosError(f"NoSuchUser: {uid}", code=-errno.ENOENT)
        users[uid]["suspended"] = suspended
        await self._save(users)

    async def user_suspend(self, uid: str) -> None:
        await self._set_suspended(uid, True)

    async def user_enable(self, uid: str) -> None:
        await self._set_suspended(uid, False)

    async def quota_set(self, uid: str, scope: str = "user",
                        max_size: int = -1,
                        max_objects: int = -1) -> None:
        """-1 = unlimited on that axis (reference quota semantics);
        setting leaves the quota disabled until quota_enable."""
        if scope not in ("user", "bucket"):
            raise RadosError(f"InvalidArgument: scope {scope!r}",
                             code=-errno.EINVAL)
        users = await self._load()
        if uid not in users:
            raise RadosError(f"NoSuchUser: {uid}", code=-errno.ENOENT)
        field = "quota" if scope == "user" else "bucket_quota"
        prev = users[uid].get(field) or {}
        users[uid][field] = {"enabled": bool(prev.get("enabled")),
                             "max_size": int(max_size),
                             "max_objects": int(max_objects)}
        await self._save(users)

    async def _quota_toggle(self, uid: str, scope: str,
                            enabled: bool) -> None:
        users = await self._load()
        if uid not in users:
            raise RadosError(f"NoSuchUser: {uid}", code=-errno.ENOENT)
        field = "quota" if scope == "user" else "bucket_quota"
        q = users[uid].get(field) or {"max_size": -1, "max_objects": -1}
        q["enabled"] = enabled
        users[uid][field] = q
        await self._save(users)

    async def quota_enable(self, uid: str, scope: str = "user") -> None:
        await self._quota_toggle(uid, scope, True)

    async def quota_disable(self, uid: str, scope: str = "user") -> None:
        await self._quota_toggle(uid, scope, False)

    async def usage(self, uid: str) -> Dict[str, int]:
        user = await self.user_info(uid)
        return await self.service.usage(user["access_key"])


def _access_key_of(headers: Dict[str, str]) -> Optional[str]:
    """The SigV4 access key naming the request's principal (verification
    already happened; this only extracts identity for ACL checks)."""
    auth = headers.get("authorization", "")
    if "Credential=" not in auth:
        return None
    try:
        return auth.split("Credential=")[1].split("/")[0]
    except IndexError:
        return None


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str = "us-east-1",
                service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request(method: str, path: str, query: str,
                      headers: Dict[str, str], signed: List[str],
                      payload_hash: str) -> str:
    canon_q = "&".join(sorted(
        f"{k}={v}" for k, v in parse_qsl(query, keep_blank_values=True)))
    canon_h = "".join(f"{h}:{headers.get(h, '').strip()}\n" for h in signed)
    return "\n".join([method, path, canon_q, canon_h, ";".join(signed),
                      payload_hash])


def sign_request(access_key: str, secret: str, method: str, path: str,
                 query: str, headers: Dict[str, str],
                 payload: bytes) -> Dict[str, str]:
    """Produce the Authorization (+x-amz-*) headers for a request — the
    client half, used by tests and any embedded S3 client."""
    amzdate = headers.get("x-amz-date", "20260101T000000Z")
    date = amzdate[:8]
    payload_hash = hashlib.sha256(payload).hexdigest()
    hdrs = dict(headers)
    hdrs["x-amz-date"] = amzdate
    hdrs["x-amz-content-sha256"] = payload_hash
    hdrs.setdefault("host", "")
    signed = sorted(["host", "x-amz-content-sha256", "x-amz-date"])
    scope = f"{date}/us-east-1/s3/aws4_request"
    creq = canonical_request(method, path, query, hdrs, signed, payload_hash)
    sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(signing_key(secret, date), sts.encode(),
                   hashlib.sha256).hexdigest()
    hdrs["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return hdrs


def presign_url(access_key: str, secret: str, method: str, path: str,
                host: str, expires: int = 3600,
                amzdate: Optional[str] = None) -> str:
    """Client half of query-string auth (reference rgw_auth_s3
    presigned URLs / AWS SigV4 query parameters): returns path?query
    that grants `method` on `path` until amzdate+expires, bearer-style
    — no headers or secret needed by the holder."""
    if amzdate is None:
        amzdate = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amzdate[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    params = [
        ("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
        ("X-Amz-Credential", f"{access_key}/{scope}"),
        ("X-Amz-Date", amzdate),
        ("X-Amz-Expires", str(int(expires))),
        ("X-Amz-SignedHeaders", "host"),
    ]
    query = "&".join(f"{k}={quote(v, safe='')}" for k, v in params)
    # sign the DECODED path (the frontend unquotes before verifying),
    # ship the ENCODED one (keys with %, spaces, etc. stay valid URLs)
    creq = canonical_request(method, path, query, {"host": host},
                             ["host"], "UNSIGNED-PAYLOAD")
    sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(signing_key(secret, date), sts.encode(),
                   hashlib.sha256).hexdigest()
    return f"{quote(path)}?{query}&X-Amz-Signature={sig}"


def verify_presigned(credentials: Dict[str, str], method: str, path: str,
                     query: str, headers: Dict[str, str],
                     now: Optional[float] = None) -> Optional[str]:
    """Server half: returns the authenticated access key, or None when
    the signature is wrong or the grant expired.  The signature covers
    method+path+query (minus the signature itself) and the host header;
    the payload is unsigned, as AWS defines for presigned uploads."""
    q = dict(parse_qsl(query, keep_blank_values=True))
    sig = q.pop("X-Amz-Signature", "")
    cred = q.get("X-Amz-Credential", "")
    access_key, _, scope = cred.partition("/")
    secret = credentials.get(access_key)
    if not sig or secret is None:
        return None
    amzdate = q.get("X-Amz-Date", "")
    try:
        import calendar
        expires = int(q.get("X-Amz-Expires", "0"))
        # amzdate is Zulu: timegm, NOT mktime (which reads local time)
        issued = calendar.timegm(time.strptime(amzdate,
                                               "%Y%m%dT%H%M%SZ"))
    except (ValueError, OverflowError):
        return None
    if now is None:
        now = time.time()
    expires = min(expires, 604800)  # AWS caps presigned life at 7 days
    if not (0 < expires and issued <= now + 300  # small clock skew
            and now <= issued + expires):
        return None
    canon_q = "&".join(f"{quote(k, safe='')}={quote(v, safe='')}"
                       for k, v in sorted(q.items()))
    creq = canonical_request(method, path, canon_q,
                             {"host": headers.get("host", "")},
                             ["host"], "UNSIGNED-PAYLOAD")
    date = scope.split("/")[0] if scope else ""
    sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    want = hmac.new(signing_key(secret, date), sts.encode(),
                    hashlib.sha256).hexdigest()
    return access_key if hmac.compare_digest(want, sig) else None


def verify_request(credentials: Dict[str, str], method: str, path: str,
                   query: str, headers: Dict[str, str],
                   payload: bytes) -> bool:
    """Server half: recompute the signature from the stored secret and
    compare (constant time)."""
    auth = headers.get("authorization", "")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        return False
    fields = dict(
        kv.strip().split("=", 1)
        for kv in auth[len("AWS4-HMAC-SHA256 "):].split(",") if "=" in kv)
    cred = fields.get("Credential", "")
    access_key, _, scope = cred.partition("/")
    secret = credentials.get(access_key)
    if secret is None:
        return False
    signed = [h for h in fields.get("SignedHeaders", "").split(";") if h]
    date = scope.split("/")[0] if scope else ""
    payload_hash = headers.get("x-amz-content-sha256", "")
    if payload_hash != hashlib.sha256(payload).hexdigest():
        return False
    creq = canonical_request(method, path, query, headers, signed,
                             payload_hash)
    sts = "\n".join(["AWS4-HMAC-SHA256", headers.get("x-amz-date", ""),
                     scope, hashlib.sha256(creq.encode()).hexdigest()])
    want = hmac.new(signing_key(secret, date), sts.encode(),
                    hashlib.sha256).hexdigest()
    return hmac.compare_digest(want, fields.get("Signature", ""))


class RgwFrontend:
    """Minimal HTTP frontend (beast role): newline-framed HTTP/1.1.

    Serves BOTH API dialects the reference gateway does: the S3-style
    routes (bucket/key paths, SigV4 when credentials are set) and the
    Swift API (reference src/rgw/rgw_rest_swift.h): tempauth-style token
    issue at /auth/v1.0 (X-Auth-User/X-Auth-Key -> X-Auth-Token +
    X-Storage-Url) and /v1/AUTH_<account>/<container>/<object> routes
    over the same bucket/object backend."""

    def __init__(self, service: RgwService):
        self.service = service
        self._server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        # Swift tempauth tokens: token -> (account, issued_monotonic);
        # TTL-bounded and size-capped (reference tempauth tokens expire)
        self._swift_tokens: Dict[str, Tuple[str, float]] = {}
        self.swift_token_ttl = 3600.0

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        await self.service.load_users()  # managed principals + statics
        self._server = await asyncio.start_server(self._serve, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1)
            except asyncio.TimeoutError:
                pass

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await reader.readline()
                if not request:
                    return
                try:
                    method, target, _ = request.decode().split(" ", 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                try:
                    length = max(0, int(headers.get("content-length", 0)))
                except ValueError:
                    writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                                 b"Content-Length: 0\r\n\r\n")
                    await writer.drain()
                    return
                if length:
                    body = await reader.readexactly(length)
                url = urlsplit(target)
                path, query = unquote(url.path), url.query
                extra: Dict[str, str] = {}
                # TTL-bounded user-store refresh: out-of-process admin
                # changes (suspend, quota) bite live gateways
                await self.service.maybe_reload_users()
                presigned = "X-Amz-Signature=" in query
                if path == "/auth/v1.0" or path.startswith("/v1/"):
                    status, payload, extra = await self._route_swift(
                        method, path, query, body, headers)
                elif presigned:
                    # query-string auth (presigned URL): the signature
                    # IS the credential — no Authorization header
                    principal = verify_presigned(
                        self.service.credentials, method, path, query,
                        headers)
                    user = self.service.user_by_access(principal)
                    if principal is None:
                        status, payload = ("403 Forbidden",
                                           b"AccessDenied")
                    elif user is not None and user.get("suspended"):
                        status, payload = ("403 Forbidden",
                                           b"UserSuspended")
                    else:
                        out = await self._route(
                            method, path, query, body, principal, headers)
                        status, payload = out[0], out[1]
                        if len(out) == 3:
                            extra.update(out[2])
                elif (self.service.credentials
                        and not verify_request(self.service.credentials,
                                               method, path, query, headers,
                                               body)):
                    status, payload = "403 Forbidden", b"SignatureDoesNotMatch"
                else:
                    # the ACL principal: the SigV4 access key that signed
                    # the request; anonymous (None) without credentials
                    principal = _access_key_of(headers)
                    user = self.service.user_by_access(principal)
                    if user is not None and user.get("suspended"):
                        status, payload = ("403 Forbidden",
                                           b"UserSuspended")
                    else:
                        out = await self._route(
                            method, path, query, body, principal, headers)
                        status, payload = out[0], out[1]
                        if len(out) == 3:
                            extra.update(out[2])
                hdr_lines = "".join(f"{k}: {v}\r\n" for k, v in extra.items())
                writer.write(
                    f"HTTP/1.1 {status}\r\nContent-Length: {len(payload)}\r\n"
                    f"{hdr_lines}"
                    f"Connection: keep-alive\r\n\r\n".encode() + payload)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _route_swift(self, method: str, path: str, query: str,
                           body: bytes, headers: Dict[str, str]
                           ) -> Tuple[str, bytes, Dict[str, str]]:
        """Swift dialect (reference rgw_rest_swift.h).  Containers map to
        buckets, objects to keys; accounts are authentication scope only
        (one backing store, as the reference's rados driver)."""
        if path == "/auth/v1.0":
            # tempauth: user "acct:user" + key -> token + storage URL
            if not self.service.credentials:
                return "501 Not Implemented", b"no credentials configured", {}
            user = headers.get("x-auth-user", "")
            key = headers.get("x-auth-key", "")
            acct = user.split(":")[0] if user else ""
            want = self.service.credentials.get(user) \
                or self.service.credentials.get(acct)
            if want is None or not hmac.compare_digest(want, key):
                return "401 Unauthorized", b"", {}
            managed = (self.service.user_by_access(user)
                       or self.service.user_by_access(acct))
            if managed is not None and managed.get("suspended"):
                return "403 Forbidden", b"UserSuspended", {}
            now = time.monotonic()
            for t, (_a, issued) in list(self._swift_tokens.items()):
                if now - issued > self.swift_token_ttl:
                    self._swift_tokens.pop(t, None)
            token = "AUTH_tk" + uuid.uuid4().hex
            self._swift_tokens[token] = (acct or user, now)
            while len(self._swift_tokens) > 10_000:
                self._swift_tokens.pop(next(iter(self._swift_tokens)))
            host, port = self.addr or ("127.0.0.1", 0)
            return "200 OK", b"", {
                "X-Auth-Token": token,
                "X-Storage-Token": token,
                "X-Storage-Url": f"http://{host}:{port}/v1/AUTH_{acct or user}",
            }
        principal = None
        if self.service.credentials:
            token = headers.get("x-auth-token", "")
            entry = self._swift_tokens.get(token)
            if entry is None or                     time.monotonic() - entry[1] > self.swift_token_ttl:
                self._swift_tokens.pop(token, None)
                return "401 Unauthorized", b"", {}
            principal = entry[0]  # the token's account, for ACL checks
            managed = self.service.user_by_access(principal)
            if managed is not None and managed.get("suspended"):
                # suspension after token issue still bites (reference:
                # every op re-checks the user record)
                return "403 Forbidden", b"UserSuspended", {}
        parts = [p for p in path.split("/") if p]
        # parts = ["v1", "AUTH_acct", container?, object...]
        if len(parts) < 2 or not parts[1].startswith("AUTH_"):
            return "400 Bad Request", b"", {}
        if len(parts) >= 3 and not (len(parts) == 3 and method == "PUT"):
            # bucket ACLs bind BOTH dialects (reference: one policy
            # store behind rgw_rest_swift and rgw_rest_s3) — container
            # creation itself is ungated, like the S3 create path
            need = "READ" if method in ("GET", "HEAD") else "WRITE"
            meta = await self.service.get_bucket_meta(parts[2])
            if not RgwService.acl_allows(meta.get("acl"), principal, need):
                return "403 Forbidden", b"AccessDenied", {}
        try:
            if len(parts) == 2:  # account: list containers
                if method in ("GET", "HEAD"):
                    names = await self.service.list_buckets()
                    extra = {"X-Account-Container-Count": str(len(names))}
                    if method == "HEAD":
                        return "204 No Content", b"", extra
                    return "200 OK", "\n".join(names).encode(), extra
                return "405 Method Not Allowed", b"", {}
            container = parts[2]
            if len(parts) == 3:
                if method == "PUT":
                    await self.service.create_bucket(container,
                                                     owner=principal)
                    return "201 Created", b"", {}
                if method in ("GET", "HEAD"):
                    index = await self.service.list_objects(container)
                    extra = {"X-Container-Object-Count": str(len(index)),
                             "X-Container-Bytes-Used": str(sum(
                                 e.get("size", 0) for e in index.values()))}
                    if method == "HEAD":
                        return "204 No Content", b"", extra
                    return "200 OK", "\n".join(sorted(index)).encode(), extra
                if method == "DELETE":
                    await self.service.delete_bucket(container)
                    return "204 No Content", b"", {}
                return "405 Method Not Allowed", b"", {}
            key = "/".join(parts[3:])
            if method == "PUT":
                # quotas bind both dialects (one store behind them)
                await self.service.check_quota(principal, container,
                                               len(body))
                await self.service.put_object(container, key, body)
                etag = hashlib.md5(body).hexdigest()
                return "201 Created", b"", {"ETag": etag}
            if method == "GET":
                rng_hdr = headers.get("range")
                if rng_hdr:
                    # same range engine AND reply shape as the S3
                    # dialect: one shared helper, zero divergence
                    return await self._ranged_get(container, key,
                                                  rng_hdr)
                data = await self.service.get_object(container, key)
                return "200 OK", data, {}
            if method == "HEAD":
                index = await self.service.list_objects(container)
                if key in index:
                    return "200 OK", b"", {
                        "Content-Length-Hint": str(index[key].get("size", 0)),
                        "ETag": index[key].get("etag", "")}
                return "404 Not Found", b"", {}
            if method == "DELETE":
                await self.service.delete_object(container, key)
                return "204 No Content", b"", {}
            return "405 Method Not Allowed", b"", {}
        except RadosError as e:
            msg = str(e)
            if "NoSuch" in msg:
                return "404 Not Found", msg.encode(), {}
            if "BucketNotEmpty" in msg:
                return "409 Conflict", msg.encode(), {}
            if "QuotaExceeded" in msg:
                return "403 Forbidden", msg.encode(), {}
            return "500 Internal Server Error", msg.encode(), {}

    async def _resolve_copy_source(self, headers: Dict[str, str],
                                   principal: Optional[str]):
        """Parse + authorize x-amz-copy-source, shared by CopyObject
        and UploadPartCopy: returns (sbucket, skey, svid) on success,
        or an (status, body) error pair — ONE copy of the source
        policy/ACL gate, so a fix to either branch cannot miss the
        other."""
        src = unquote(headers["x-amz-copy-source"])
        src_path, _, src_q = src.partition("?")
        sparts = [p for p in src_path.split("/") if p]
        if len(sparts) < 2:
            return None, ("400 Bad Request",
                          b"InvalidArgument: copy-source")
        sbucket, skey = sparts[0], "/".join(sparts[1:])
        svid = dict(parse_qsl(src_q)).get("versionId")
        smeta = await self.service.get_bucket_meta(sbucket)
        sverdict = RgwService.policy_eval(
            smeta.get("policy"), principal, "s3:GetObject",
            f"arn:aws:s3:::{sbucket}/{skey}")
        if sverdict == "Deny" or (
                sverdict != "Allow" and not RgwService.acl_allows(
                    smeta.get("acl"), principal, "READ")):
            return None, ("403 Forbidden", b"AccessDenied")
        return (sbucket, skey, svid), None

    async def _ranged_get(self, bucket: str, key: str, rng_hdr: str,
                          version_id: Optional[str] = None):
        """Range GET reply, shared by the S3 and Swift dialects: 206 +
        Content-Range for a satisfiable range, 416 + 'bytes */total'
        when past the end, plain 200 for a malformed spec."""
        try:
            data, total, rng = await self.service.get_object_range(
                bucket, key, rng_hdr, version_id=version_id)
        except RadosError as e:
            if e.code == -errno.ERANGE:
                total = getattr(e, "total", None)
                if total is None:
                    total = await self.service.stat_object(
                        bucket, key, version_id=version_id)
                return ("416 Requested Range Not Satisfiable",
                        b"InvalidRange",
                        {"Content-Range": f"bytes */{total}"})
            raise
        if rng is None:
            return "200 OK", data, {}
        a, b = rng
        return ("206 Partial Content", data,
                {"Content-Range": f"bytes {a}-{b}/{total}",
                 "Accept-Ranges": "bytes"})

    async def _route(self, method: str, path: str, query: str,
                     body: bytes,
                     principal: Optional[str] = None,
                     headers: Optional[Dict[str, str]] = None):
        """Returns (status, payload) or (status, payload, extra
        response headers) — Range GETs carry Content-Range."""
        headers = headers or {}
        parts = [p for p in path.split("/") if p]
        q = dict(parse_qsl(query, keep_blank_values=True))
        try:
            if not parts:
                if method == "GET":
                    return "200 OK", json.dumps(
                        await self.service.list_buckets()).encode()
                return "405 Method Not Allowed", b""
            bucket = parts[0]
            # authorization gate (reference rgw_op verify_permission):
            # the bucket POLICY is consulted first — explicit Deny wins,
            # explicit Allow grants, and no match falls through to the
            # ACL (reads need READ, mutations WRITE).  Administrative
            # subresources (acl/versioning/lifecycle/policy mutations)
            # are owner-level and deliberately NOT policy-gated, so a
            # bad Deny statement can never lock the owner out of
            # repairing the policy (AWS root-user semantics).
            gate_meta = None
            if parts and method in ("GET", "HEAD", "PUT", "POST", "DELETE"):
                need = "READ" if method in ("GET", "HEAD") else "WRITE"
                # GET ?acl / ?policy are READ_ACP-class subresources
                # (AWS: READ_ACP / s3:GetBucketPolicy is owner-level) —
                # a plain read grantee must not be able to enumerate
                # grants or the policy document, so they share the
                # owner-level gate with the mutating admin ops.
                admin_op = (method in ("PUT", "DELETE") and q.keys() & {
                    "acl", "versioning", "lifecycle", "policy"}) or (
                    method == "GET" and q.keys() & {"acl", "policy"})
                if admin_op:
                    need = "FULL_CONTROL"
                is_create = len(parts) == 1 and method == "PUT" \
                    and not q.keys() & {"versioning", "lifecycle", "acl",
                                        "policy"}
                if not is_create:
                    gate_meta = await self.service.get_bucket_meta(bucket)
                    if len(parts) >= 2:
                        action = {"GET": "s3:GetObject",
                                  "HEAD": "s3:GetObject",
                                  "PUT": "s3:PutObject",
                                  "POST": "s3:PutObject",
                                  "DELETE": "s3:DeleteObject"}[method]
                        resource = f"arn:aws:s3:::{bucket}/" + \
                            "/".join(parts[1:])
                    else:
                        action = {"GET": "s3:ListBucket",
                                  "HEAD": "s3:ListBucket",
                                  "PUT": "s3:CreateBucket",
                                  "POST": "s3:PutObject",
                                  "DELETE": "s3:DeleteBucket"}[method]
                        resource = f"arn:aws:s3:::{bucket}"
                    verdict = None
                    if not admin_op:
                        verdict = RgwService.policy_eval(
                            gate_meta.get("policy"), principal, action,
                            resource)
                    if verdict == "Deny":
                        return "403 Forbidden", b"AccessDenied"
                    if verdict != "Allow" and not RgwService.acl_allows(
                            gate_meta.get("acl"), principal, need):
                        return "403 Forbidden", b"AccessDenied"
            if len(parts) == 1:
                if method == "PUT" and "versioning" in q:
                    cfg = json.loads(body or b"{}")
                    await self.service.set_versioning(
                        bucket, cfg.get("Status") == "Enabled")
                    return "200 OK", b""
                if method == "GET" and "versioning" in q:
                    meta = await self.service.get_bucket_meta(bucket)
                    return "200 OK", json.dumps(
                        {"Status": "Enabled" if meta.get("versioning")
                         else "Suspended"}).encode()
                if method == "PUT" and "lifecycle" in q:
                    rules = json.loads(body or b"[]")
                    await self.service.put_lifecycle(bucket, rules)
                    return "200 OK", b""
                if method == "GET" and "lifecycle" in q:
                    meta = await self.service.get_bucket_meta(bucket)
                    return "200 OK", json.dumps(
                        meta.get("lifecycle") or []).encode()
                if method == "PUT" and "acl" in q:
                    acl = json.loads(body or b"{}")
                    await self.service.put_bucket_acl(bucket, acl)
                    return "200 OK", b""
                if method == "GET" and "acl" in q:
                    meta = await self.service.get_bucket_meta(bucket)
                    return "200 OK", json.dumps(meta.get("acl")).encode()
                if method == "PUT" and "policy" in q:
                    try:
                        doc = json.loads(body or b"{}")
                    except ValueError:
                        return "400 Bad Request", b"MalformedPolicy"
                    await self.service.put_bucket_policy(bucket, doc)
                    return "200 OK", b""
                if method == "GET" and "policy" in q:
                    meta = await self.service.get_bucket_meta(bucket)
                    if not meta.get("policy"):
                        return "404 Not Found", b"NoSuchBucketPolicy"
                    return "200 OK", json.dumps(meta["policy"]).encode()
                if method == "DELETE" and "policy" in q:
                    await self.service.delete_bucket_policy(bucket)
                    return "204 No Content", b""
                if method == "GET" and "versions" in q:
                    return "200 OK", json.dumps(
                        await self.service.list_object_versions(
                            bucket)).encode()
                if method == "GET" and "uploads" in q:
                    return "200 OK", json.dumps({
                        "Uploads":
                        await self.service.list_multipart_uploads(
                            bucket)}).encode()
                if method == "PUT":
                    await self.service.create_bucket(bucket,
                                                     owner=principal)
                    return "200 OK", b""
                if method == "GET":
                    return "200 OK", json.dumps(
                        await self.service.list_objects(bucket)).encode()
                if method == "DELETE":
                    await self.service.delete_bucket(bucket)
                    return "204 No Content", b""
                return "405 Method Not Allowed", b""
            key = "/".join(parts[1:])
            if method == "POST" and "uploads" in q:
                upload_id = await self.service.initiate_multipart(bucket, key)
                return "200 OK", json.dumps({"UploadId": upload_id}).encode()
            if method == "POST" and "uploadId" in q:
                order = None
                if body:
                    try:
                        order = [int(n) for n in json.loads(body)["Parts"]]
                    except (ValueError, KeyError, TypeError):
                        return "400 Bad Request", b"MalformedXML"
                etag = await self.service.complete_multipart(
                    bucket, q["uploadId"], order, principal=principal)
                return "200 OK", json.dumps({"ETag": etag}).encode()
            if method == "PUT" and "uploadId" in q and "partNumber" in q:
                try:
                    part = int(q["partNumber"])
                except ValueError:
                    return "400 Bad Request", b"InvalidArgument: partNumber"
                if headers.get("x-amz-copy-source"):
                    # UploadPartCopy (reference RGWCopyObj part mode):
                    # the part bytes come from an existing object, with
                    # an optional x-amz-copy-source-range — silently
                    # staging the empty request body instead would
                    # complete into a truncated object
                    resolved, err = await self._resolve_copy_source(
                        headers, principal)
                    if err is not None:
                        return err
                    sbucket, skey, svid = resolved
                    src_rng = headers.get("x-amz-copy-source-range")
                    if src_rng:
                        try:
                            body, _total, rng = \
                                await self.service.get_object_range(
                                    sbucket, skey, src_rng,
                                    version_id=svid)
                        except RadosError as e:
                            if e.code == -errno.ERANGE:
                                # unsatisfiable source range: the S3
                                # contract is 416, never a 500
                                return ("416 Requested Range Not "
                                        "Satisfiable", b"InvalidRange")
                            raise
                        if rng is None:
                            return ("400 Bad Request",
                                    b"InvalidArgument: copy-source-range")
                    else:
                        body = await self.service.get_object(
                            sbucket, skey, version_id=svid)
                # staged parts are quota-charged too (against indexed
                # usage — a bound, not exact accounting), or a capped
                # user could park unlimited bytes in never-completed
                # uploads
                await self.service.check_quota(principal, bucket,
                                               len(body), add_objects=0)
                etag = await self.service.upload_part(
                    bucket, q["uploadId"], part, body)
                return "200 OK", json.dumps({"ETag": etag}).encode()
            if method == "DELETE" and "uploadId" in q:
                await self.service.abort_multipart(bucket, q["uploadId"])
                return "204 No Content", b""
            if method == "GET" and "uploadId" in q:
                # key must match the upload's target: the per-object
                # authorization gate above was evaluated against it
                return "200 OK", json.dumps({
                    "Parts": await self.service.list_parts(
                        bucket, q["uploadId"], key=key)}).encode()
            if method == "PUT" and "tagging" in q:
                try:
                    parsed = json.loads(body or b"{}")
                except ValueError:
                    return "400 Bad Request", b"MalformedXML"
                if not isinstance(parsed, dict) or not isinstance(
                        parsed.get("TagSet", {}), dict):
                    return "400 Bad Request", b"MalformedXML"
                await self.service.put_object_tagging(
                    bucket, key, parsed.get("TagSet", {}))
                return "200 OK", b""
            if method == "GET" and "tagging" in q:
                tags = await self.service.get_object_tagging(bucket, key)
                return "200 OK", json.dumps({"TagSet": tags}).encode()
            if method == "DELETE" and "tagging" in q:
                await self.service.delete_object_tagging(bucket, key)
                return "204 No Content", b""
            if method == "PUT" and headers.get("x-amz-copy-source"):
                # server-side copy (reference RGWCopyObj): the caller
                # needs WRITE on the destination (already gated above)
                # AND read access to the SOURCE bucket/key
                resolved, err = await self._resolve_copy_source(
                    headers, principal)
                if err is not None:
                    return err
                sbucket, skey, svid = resolved
                out = await self.service.copy_object(
                    sbucket, skey, bucket, key, version_id=svid,
                    principal=principal)
                return "200 OK", json.dumps(out).encode()
            if method == "PUT":
                await self.service.check_quota(principal, bucket,
                                               len(body))
                vid = await self.service.put_object(bucket, key, body,
                                                    bmeta=gate_meta)
                return "200 OK", (json.dumps({"VersionId": vid}).encode()
                                  if vid else b"")
            if method == "GET":
                rng_hdr = headers.get("range")
                if rng_hdr:
                    return await self._ranged_get(
                        bucket, key, rng_hdr,
                        version_id=q.get("versionId"))
                return "200 OK", await self.service.get_object(
                    bucket, key, version_id=q.get("versionId"))
            if method == "HEAD":
                index = await self.service.list_objects(bucket)
                if key in index:
                    return "200 OK", b""
                return "404 Not Found", b""
            if method == "DELETE":
                await self.service.delete_object(
                    bucket, key, version_id=q.get("versionId"),
                    bmeta=gate_meta)
                return "204 No Content", b""
            return "405 Method Not Allowed", b""
        except RadosError as e:
            msg = str(e)
            if "NoSuch" in msg:
                return "404 Not Found", msg.encode()
            if "BucketNotEmpty" in msg:
                return "409 Conflict", msg.encode()
            if "InvalidPart" in msg or "MalformedXML" in msg \
                    or "MalformedPolicy" in msg or "InvalidTag" in msg \
                    or "InvalidArgument" in msg:
                return "400 Bad Request", msg.encode()
            if "MethodNotAllowed" in msg:
                return "405 Method Not Allowed", msg.encode()
            if "QuotaExceeded" in msg:
                return "403 Forbidden", msg.encode()
            return "500 Internal Server Error", msg.encode()


# -- multisite sync (reference src/rgw/driver/rados/rgw_sync.cc: zones
#    replicate via datalog/bilog replay) -------------------------------------

DATALOG_OID = ".rgw.datalog"


class ZoneSyncAgent:
    """radosgw sync agent role: replays one zone's data log into another
    zone, resumably.  The source gateway appends an entry per mutation
    (the reference's datalog/bucket-index-log pair collapsed into one
    ordered log); the agent reads entries past its persisted position,
    fetches the referenced objects from the source, and applies them to
    the destination — full-sync bootstrap first, then incremental tail,
    exactly the reference's full-sync -> incremental state machine in
    miniature."""

    def __init__(self, src: RgwService, dst: RgwService,
                 zone_id: str = "zone"):
        self.src = src
        self.dst = dst
        self.zone_id = zone_id

    def _pos_oid(self) -> str:
        return f".rgw.sync.pos.{self.zone_id}"

    async def _load_pos(self) -> int:
        try:
            return json.loads(await self.dst.ioctx.read(self._pos_oid()))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            return -1

    async def sync(self) -> int:
        """Apply new source mutations to the destination; returns the
        number applied.  First contact runs a FULL SYNC of every bucket
        (log history may predate this zone), then tails the log."""
        pos = await self._load_pos()
        state = await self.src.datalog_state()
        log, trimmed = state["log"], state.get("trimmed", 0)
        if 0 <= pos < trimmed:
            pos = -1  # fell behind the trim floor: full re-sync
        # replicated applies must not re-enter the DESTINATION's datalog:
        # in active-active topologies the echo would ping-pong forever.
        # Scoped to THIS task (contextvar): concurrent local mutations on
        # the destination gateway keep logging normally.
        token = _DATALOG_SUPPRESS.set(True)
        try:
            if pos < 0:
                src_buckets = set(await self.src.list_buckets())
                for bucket in sorted(src_buckets):
                    await self.dst.create_bucket(bucket)
                    src_keys = set(await self.src.list_objects(bucket))
                    for key in sorted(src_keys):
                        data = await self.src.get_object(bucket, key)
                        await self.dst.put_object(bucket, key, data)
                    # deletions the trimmed log no longer tells us about
                    for key in set(await self.dst.list_objects(bucket))                             - src_keys:
                        await self.dst.delete_object(bucket, key)
                for bucket in set(await self.dst.list_buckets())                         - src_buckets:
                    for key in await self.dst.list_objects(bucket):
                        await self.dst.delete_object(bucket, key)
                    await self.dst.delete_bucket(bucket)
                pos = log[-1]["seq"] if log else trimmed
                await self.dst.ioctx.write_full(self._pos_oid(),
                                                json.dumps(pos).encode())
                return 0
            applied = 0
            for ev in log:
                if ev["seq"] <= pos:
                    continue
                bucket, key, op = ev["bucket"], ev.get("key"), ev["op"]
                try:
                    if op == "create_bucket":
                        await self.dst.create_bucket(bucket)
                    elif op == "delete_bucket":
                        await self.dst.delete_bucket(bucket)
                    elif op == "put":
                        data = await self.src.get_object(bucket, key)
                        await self.dst.create_bucket(bucket)
                        await self.dst.put_object(bucket, key, data)
                    elif op == "resync":
                        # version-targeted mutations change the key's
                        # current state in a source-only way: mirror the
                        # VISIBLE state (present -> copy, absent -> del)
                        try:
                            data = await self.src.get_object(bucket, key)
                        except RadosError as e:
                            if e.code != -errno.ENOENT \
                                    and "NoSuch" not in str(e):
                                raise
                            data = None
                        if data is None:
                            try:
                                await self.dst.delete_object(bucket, key)
                            except RadosError:
                                pass
                        else:
                            await self.dst.create_bucket(bucket)
                            await self.dst.put_object(bucket, key, data)
                    elif op == "delete":
                        await self.dst.delete_object(bucket, key)
                except RadosError as e:
                    # the source object may be gone again (put then
                    # delete before we synced): a later entry covers it
                    if e.code != -errno.ENOENT and "NoSuch" not in str(e):
                        raise
                pos = ev["seq"]
                applied += 1
            if applied:
                await self.dst.ioctx.write_full(self._pos_oid(),
                                                json.dumps(pos).encode())
            return applied
        finally:
            _DATALOG_SUPPRESS.reset(token)
