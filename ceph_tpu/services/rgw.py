"""RGW-lite: S3-style object gateway over RADOS.

Role-equivalent of the reference's RGW core request path (reference
src/rgw/): an asyncio HTTP frontend (the beast frontend role) maps
S3-shaped requests onto RADOS — buckets are index objects, object data is
striped over RADOS objects (rgw_max_chunk_size-style chunking via the
striper), and listings come from the bucket index, not pool scans, exactly
the reference's bucket-index discipline.

API subset: PUT /b (create bucket), GET / (list buckets), PUT /b/k,
GET /b/k, DELETE /b/k, GET /b (list objects), HEAD /b/k.  Divergence by
design: no S3 auth/multipart/versioning/multisite.
"""

from __future__ import annotations

import asyncio
import json
from typing import Dict, List, Optional, Tuple
from urllib.parse import unquote

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx
from ceph_tpu.rados.striper import RadosStriper

BUCKETS_ROOT = ".rgw.buckets"  # registry of buckets


class RgwService:
    """Bucket/object operations (usable directly or via the HTTP frontend)."""

    def __init__(self, ioctx: IoCtx, chunk_size: int = 1 << 20):
        self.ioctx = ioctx
        self.striper = RadosStriper(ioctx, object_size=chunk_size)

    @staticmethod
    def _index_oid(bucket: str) -> str:
        return f".bucket.index.{bucket}"

    async def _load_index(self, bucket: str) -> Optional[Dict[str, Dict]]:
        try:
            return json.loads(await self.ioctx.read(self._index_oid(bucket)))
        except RadosError:
            return None

    async def _save_index(self, bucket: str, index: Dict[str, Dict]) -> None:
        await self.ioctx.write_full(self._index_oid(bucket),
                                    json.dumps(index).encode())

    async def create_bucket(self, bucket: str) -> None:
        if await self._load_index(bucket) is None:
            await self._save_index(bucket, {})
            buckets = await self.list_buckets()
            if bucket not in buckets:
                buckets.append(bucket)
                await self.ioctx.write_full(
                    BUCKETS_ROOT, json.dumps(sorted(buckets)).encode())

    async def list_buckets(self) -> List[str]:
        try:
            return json.loads(await self.ioctx.read(BUCKETS_ROOT))
        except RadosError:
            return []

    async def put_object(self, bucket: str, key: str, data: bytes) -> None:
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        await self.striper.write(f"{bucket}/{key}", data)
        index[key] = {"size": len(data)}
        await self._save_index(bucket, index)

    async def get_object(self, bucket: str, key: str) -> bytes:
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        if key not in index:
            raise RadosError(f"NoSuchKey: {key}")
        return await self.striper.read(f"{bucket}/{key}")

    async def delete_object(self, bucket: str, key: str) -> None:
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        index.pop(key, None)
        await self.striper.remove(f"{bucket}/{key}")
        await self._save_index(bucket, index)

    async def list_objects(self, bucket: str) -> Dict[str, Dict]:
        index = await self._load_index(bucket)
        if index is None:
            raise RadosError(f"NoSuchBucket: {bucket}")
        return index


class RgwFrontend:
    """Minimal HTTP frontend (beast role): newline-framed HTTP/1.1."""

    def __init__(self, service: RgwService):
        self.service = service
        self._server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[Tuple[str, int]] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._serve, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1)
            except asyncio.TimeoutError:
                pass

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await reader.readline()
                if not request:
                    return
                try:
                    method, path, _ = request.decode().split(" ", 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                try:
                    length = max(0, int(headers.get("content-length", 0)))
                except ValueError:
                    writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                                 b"Content-Length: 0\r\n\r\n")
                    await writer.drain()
                    return
                if length:
                    body = await reader.readexactly(length)
                status, payload = await self._route(method, unquote(path), body)
                writer.write(
                    f"HTTP/1.1 {status}\r\nContent-Length: {len(payload)}\r\n"
                    f"Connection: keep-alive\r\n\r\n".encode() + payload)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _route(self, method: str, path: str,
                     body: bytes) -> Tuple[str, bytes]:
        parts = [p for p in path.split("/") if p]
        try:
            if not parts:
                if method == "GET":
                    return "200 OK", json.dumps(
                        await self.service.list_buckets()).encode()
                return "405 Method Not Allowed", b""
            bucket = parts[0]
            if len(parts) == 1:
                if method == "PUT":
                    await self.service.create_bucket(bucket)
                    return "200 OK", b""
                if method == "GET":
                    return "200 OK", json.dumps(
                        await self.service.list_objects(bucket)).encode()
                return "405 Method Not Allowed", b""
            key = "/".join(parts[1:])
            if method == "PUT":
                await self.service.put_object(bucket, key, body)
                return "200 OK", b""
            if method == "GET":
                return "200 OK", await self.service.get_object(bucket, key)
            if method == "HEAD":
                index = await self.service.list_objects(bucket)
                if key in index:
                    return "200 OK", b""
                return "404 Not Found", b""
            if method == "DELETE":
                await self.service.delete_object(bucket, key)
                return "204 No Content", b""
            return "405 Method Not Allowed", b""
        except RadosError as e:
            msg = str(e)
            if "NoSuch" in msg:
                return "404 Not Found", msg.encode()
            return "500 Internal Server Error", msg.encode()
