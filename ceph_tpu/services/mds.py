"""MDS-lite: a POSIX-style file namespace over RADOS.

Role-equivalent of the reference's CephFS metadata path in miniature
(reference src/mds/, src/client/): directories are metadata objects
holding dentries (the CInode/CDir/CDentry cache's persistent form —
reference stores dirfrags as omap on meta-pool objects); file data is
striped over data-pool objects exactly like the reference's
``<ino>.<frag>`` layout (via the striper).  The API mirrors libcephfs's
shape: mkdir/listdir/stat/write/read/unlink/rename.

Journaling (reference src/mds/MDLog.cc + osdc/Journaler): every
metadata mutation appends a journal EVENT to a segmented journal in the
metadata pool BEFORE the dirfrag updates are written.  Events record
idempotent POST-state (set/remove this dentry, ensure/remove this dir),
so a standby taking over after a crash calls ``mount()``, which replays
every unexpired event — completing half-applied multi-object updates —
exactly the reference's up:replay stage.  Fully applied positions are
expired (LogSegment trim) and their segments removed.

Multi-active MDS lives in :mod:`ceph_tpu.services.mds_cluster`
(subtree partitioning across ranks, journaled export/import, balancer,
rank failover); this module is the single-rank core it composes.
"""

from __future__ import annotations

import asyncio
import errno
import json
import posixpath
import struct
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx
from ceph_tpu.rados.striper import RadosStriper

SEGMENT_EVENTS = 128  # events per journal segment (LogSegment role)
_REC = struct.Struct("<I")  # length prefix per journal record


def is_under(path: str, root: str) -> bool:
    """True if `path` is `root` or inside it (component-wise)."""
    return path == root or (root == "/" and path.startswith("/")) \
        or path.startswith(root + "/")


class FsError(Exception):
    pass


class MDLog:
    """Segmented metadata journal (reference MDLog/Journaler): append
    length-prefixed JSON events to segment objects; replay probes each
    segment to its end (a torn tail terminates the scan, exactly the
    reference's journal-end probe); expiry advances past applied events
    and removes fully expired segments."""

    HEAD_OID = "mds_journal_head"

    def __init__(self, ioctx: IoCtx, prefix: str = ""):
        # `prefix` names WHOSE journal this is: multi-active MDS gives
        # each rank its own journal objects (the reference's per-rank
        # 20X.xxxx journal inodes), so rank recovery replays only its
        # own log
        self.ioctx = ioctx
        self.prefix = prefix
        self.seg = 0          # segment being appended
        self.off = 0          # byte offset within it
        self.expire_seg = 0   # first segment that may hold unapplied events
        self.count = 0        # events in the current segment

    def _seg_oid(self, seg: int) -> str:
        return f"{self.prefix}mds_journal.{seg:08d}"

    async def _save_head(self) -> None:
        await self.ioctx.write_full(self.prefix + self.HEAD_OID, json.dumps(
            {"expire_seg": self.expire_seg, "write_seg": self.seg}).encode())

    async def load(self) -> List[Dict]:
        """Read the head and scan unexpired segments; positions the
        append cursor at the end.  Returns every event that may not have
        been fully applied (mount() replays them)."""
        try:
            head = json.loads(await self.ioctx.read(self.prefix
                                                    + self.HEAD_OID))
        except RadosError as e:
            # a fresh journal is only the right answer for VERIFIED
            # absence; resetting the cursor on a transient read failure
            # would replay from scratch / lose the append position
            if e.code != -errno.ENOENT:
                raise
            head = {"expire_seg": 0, "write_seg": 0}
        self.expire_seg = head["expire_seg"]
        events: List[Dict] = []
        seg = self.expire_seg
        last_seg, last_off, last_count = head["write_seg"], 0, 0
        while True:
            try:
                blob = await self.ioctx.read(self._seg_oid(seg))
            except RadosError:
                if seg <= head["write_seg"]:
                    seg += 1  # removed/expired segment inside the window
                    continue
                break
            off = count = 0
            while off + _REC.size <= len(blob):
                (n,) = _REC.unpack_from(blob, off)
                if off + _REC.size + n > len(blob):
                    break  # torn tail: journal ends here
                try:
                    events.append(json.loads(
                        blob[off + _REC.size:off + _REC.size + n]))
                except ValueError:
                    break
                off += _REC.size + n
                count += 1
            last_seg, last_off, last_count = seg, off, count
            seg += 1
        self.seg, self.off, self.count = last_seg, last_off, last_count
        return events

    async def append(self, event: Dict) -> None:
        if self.count >= SEGMENT_EVENTS:
            self.seg += 1
            self.off = 0
            self.count = 0
            await self._save_head()
        rec = json.dumps(event).encode()
        await self.ioctx.write(self._seg_oid(self.seg),
                               _REC.pack(len(rec)) + rec, offset=self.off)
        self.off += _REC.size + len(rec)
        self.count += 1

    async def expire(self) -> None:
        """Everything appended so far is applied: move the expiry floor
        to the current segment and drop older segments (LogSegment
        expiry)."""
        if self.expire_seg == self.seg:
            return
        old, self.expire_seg = self.expire_seg, self.seg
        await self._save_head()
        for s in range(old, self.expire_seg):
            try:
                await self.ioctx.remove(self._seg_oid(s))
            except RadosError:
                pass

    async def roll(self) -> None:
        """Close the CURRENT segment (start a fresh one), so a following
        expire() retires every event appended so far — expire() alone
        cannot drop the in-progress segment.  Subtree export uses this
        as its flush barrier: after roll+expire, nothing a replay could
        re-apply refers to the migrated subtree."""
        if self.count == 0 and self.off == 0:
            return  # current segment already empty
        self.seg += 1
        self.off = 0
        self.count = 0
        await self._save_head()


class FileSystem:
    def __init__(self, meta_ioctx: IoCtx, data_ioctx: Optional[IoCtx] = None,
                 object_size: int = 1 << 22, journal: bool = True,
                 journal_prefix: str = ""):
        self.meta = meta_ioctx
        self.data = data_ioctx or meta_ioctx
        self.striper = RadosStriper(self.data, object_size=object_size)
        self.mdlog: Optional[MDLog] = (
            MDLog(meta_ioctx, journal_prefix) if journal else None)
        self._applied_since_expire = 0
        # serializes this rank's metadata mutations: dirfrag updates are
        # read-modify-write of one dentries object, so two interleaved
        # ops on the same directory would lose the first update (the
        # reference serializes through per-CDir locks under the mds_lock)
        self._mutate = asyncio.Lock()
        self._snap_cache: Optional[Dict[str, Dict]] = None

    async def mount(self) -> int:
        """Recover the namespace: replay unexpired journal events (the
        up:replay stage a standby runs at takeover).  Returns the number
        of events replayed.  Safe to call on a fresh filesystem."""
        if self.mdlog is None:
            return 0
        events = await self.mdlog.load()
        for ev in events:
            await self._apply_event(ev)
        if events:
            await self.mdlog.expire()
        return len(events)

    # -- journal ------------------------------------------------------------

    async def _journal(self, event: Dict) -> None:
        if self.mdlog is not None:
            await self.mdlog.append(event)

    async def _journal_applied(self) -> None:
        """Called after an op's dirfrag updates landed: periodically
        expire the journal so replay stays short (the reference expires
        segments whose events are all flushed)."""
        if self.mdlog is None:
            return
        self._applied_since_expire += 1
        if self._applied_since_expire >= SEGMENT_EVENTS:
            self._applied_since_expire = 0
            await self.mdlog.expire()

    async def _apply_event(self, ev: Dict) -> None:
        """Idempotent replay of one journal event: events carry POST-
        state, so applying an already-applied event is a no-op."""
        op = ev.get("op")
        if op == "set_dentry":
            if ev.get("mkdir"):
                if await self._load_dir(ev["mkdir"]) is None:
                    await self._save_dir(ev["mkdir"], {})
            dentries = await self._load_dir(ev["parent"])
            if dentries is None:
                return  # parent itself gone (later event removed it)
            dentries[ev["name"]] = ev["dentry"]
            await self._save_dir(ev["parent"], dentries)
            old_ino = ev.get("drop_old_ino")
            if old_ino and old_ino != ev["dentry"].get("ino"):
                # whole-file replace: the superseded inode's data goes
                # with the same event (idempotent: already-gone is fine)
                try:
                    await self.striper.remove(self._file_oid(old_ino))
                except RadosError:
                    pass
        elif op == "setattr_dentry":
            dentries = await self._load_dir(ev["parent"])
            if dentries is not None and ev["name"] in dentries:
                dentries[ev["name"]].update(ev["attrs"])
                await self._save_dir(ev["parent"], dentries)
        elif op == "rm_dentry":
            dentries = await self._load_dir(ev["parent"])
            if dentries is not None and ev["name"] in dentries:
                del dentries[ev["name"]]
                await self._save_dir(ev["parent"], dentries)
            if ev.get("rmdir"):
                try:
                    await self.meta.remove(self._dir_oid(ev["rmdir"]))
                except RadosError:
                    pass
            if ev.get("drop_ino"):
                try:
                    await self.striper.remove(self._file_oid(ev["drop_ino"]))
                except RadosError:
                    pass
        elif op == "drop_ino":
            try:
                await self.striper.remove(self._file_oid(ev["ino"]))
            except RadosError:
                pass
        elif op == "rename":
            for sub in ev["events"]:
                await self._apply_event(sub)
        elif op == "rename_dir":
            # ordering for lock-free readers: write every destination
            # dirfrag from the JOURNALED post-state, flip the parent
            # dentries (dst set, src rm — never-neither), THEN delete
            # the old dirfrag objects.  At every point the namespace
            # resolves: pre-flip readers walk src over still-present
            # old frags, post-flip readers walk dst over the new ones.
            sdentries = await self._load_dir(ev["sparent"])
            fully_applied = (sdentries is not None
                             and ev["sname"] not in sdentries)
            if not fully_applied:
                for rel, frag in ev["frags"].items():
                    new_path = posixpath.join(ev["dst"], rel) if rel \
                        else ev["dst"]
                    await self._save_dir(new_path, frag)
                ddentries = await self._load_dir(ev["dparent"])
                if ddentries is not None:
                    ddentries[ev["dname"]] = ev["dentry"]
                    await self._save_dir(ev["dparent"], ddentries)
                sdentries = await self._load_dir(ev["sparent"])
                if sdentries is not None and ev["sname"] in sdentries:
                    del sdentries[ev["sname"]]
                    await self._save_dir(ev["sparent"], sdentries)
            # old-frag cleanup (also on replay after a crash between the
            # flip and the deletes): remove a source object only if its
            # CONTENT matches the journaled post-state — a re-created
            # directory at the old path has different contents and is
            # left alone (content-addressed idempotency)
            for rel, frag in ev["frags"].items():
                old_path = posixpath.join(ev["src"], rel) if rel \
                    else ev["src"]
                cur = await self._load_dir(old_path)
                if cur is None or (fully_applied and cur != frag):
                    continue
                try:
                    await self.meta.remove(self._dir_oid(old_path))
                except RadosError:
                    pass
        elif op == "snap_create":
            table = await self._load_snaptable()
            table[ev["key"]] = {"root": ev["root"], "name": ev["name"],
                                "created": ev.get("created", 0.0),
                                "tree": ev["tree"]}
            await self._save_snaptable(table)
        elif op == "snap_delete":
            table = await self._load_snaptable()
            if ev["key"] in table:
                del table[ev["key"]]
                await self._save_snaptable(table)
            for ino in ev.get("drop", ()):
                try:
                    await self.striper.remove(self._file_oid(ino))
                except RadosError:
                    pass

    # -- dentries ------------------------------------------------------------

    @staticmethod
    def _norm(path: str) -> str:
        p = posixpath.normpath("/" + path.strip("/"))
        return p

    @staticmethod
    def _dir_oid(path: str) -> str:
        return f"dir:{path}"

    @staticmethod
    def _file_oid(ino: str) -> str:
        # data rides an IMMUTABLE inode id (the reference's <ino>.<frag>
        # layout), so rename never touches data objects
        return f"ino:{ino}"

    async def _load_dir(self, path: str) -> Optional[Dict[str, Dict]]:
        try:
            return json.loads(await self.meta.read(self._dir_oid(path)))
        except RadosError:
            return None

    async def _save_dir(self, path: str, dentries: Dict[str, Dict]) -> None:
        await self.meta.write_full(self._dir_oid(path),
                                   json.dumps(dentries).encode())

    async def mkfs(self) -> None:
        if await self._load_dir("/") is None:
            await self._save_dir("/", {})

    async def _parent_of(self, path: str):
        parent = posixpath.dirname(path)
        name = posixpath.basename(path)
        dentries = await self._load_dir(parent)
        if dentries is None:
            raise FsError(f"ENOENT: parent {parent}")
        return parent, name, dentries

    # -- namespace ops -------------------------------------------------------

    async def mkdir(self, path: str, owner: Optional[str] = None) -> None:
        path = self._norm(path)
        if path == "/":
            raise FsError("EEXIST: /")
        async with self._mutate:
            parent, name, dentries = await self._parent_of(path)
            if name in dentries:
                raise FsError(f"EEXIST: {path}")
            # no umask model: creations default to world-rw (0777/0666
            # like a 000-umask process) so multi-client workflows keep
            # working until an owner narrows with chmod
            dentry = {"type": "dir", "mtime": time.time(), "mode": 0o777}
            if owner is not None:
                dentry["owner"] = owner
            event = {"op": "set_dentry", "parent": parent, "name": name,
                     "mkdir": path, "dentry": dentry}
            await self._journal(event)
            await self._apply_event(event)
            await self._journal_applied()

    async def chmod(self, path: str, mode: int,
                    requester: Optional[str] = None) -> None:
        """Journaled permission-bit update (reference CInode mode +
        MClientRequest setattr): merges into the dentry, preserving
        everything else.  The ownership gate runs HERE, under _mutate,
        against the dentry the change will land on — a check-then-act
        pair outside the lock would race a rename re-binding the
        path."""
        path = self._norm(path)
        if path == "/":
            raise FsError("EPERM: cannot chmod /")
        async with self._mutate:
            parent, name, dentries = await self._parent_of(path)
            if name not in dentries:
                raise FsError(f"ENOENT: {path}")
            owner = dentries[name].get("owner")
            if requester is not None and owner is not None \
                    and owner != requester:
                raise FsError(f"EPERM: {path} owned by {owner}")
            event = {"op": "setattr_dentry", "parent": parent,
                     "name": name, "attrs": {"mode": int(mode) & 0o7777}}
            await self._journal(event)
            await self._apply_event(event)
            await self._journal_applied()

    async def listdir(self, path: str) -> List[str]:
        path = self._norm(path)
        dentries = await self._load_dir(path)
        if dentries is None:
            raise FsError(f"ENOENT: {path}")
        return sorted(dentries)

    async def stat(self, path: str) -> Dict:
        path = self._norm(path)
        if path == "/":
            return {"type": "dir"}
        parent, name, dentries = await self._parent_of(path)
        if name not in dentries:
            raise FsError(f"ENOENT: {path}")
        return dict(dentries[name])

    async def write_file(self, path: str, data: bytes,
                         owner: Optional[str] = None) -> None:
        path = self._norm(path)
        # data rides a FRESH inode, written OUTSIDE the rank mutation
        # lock: bulk data transfers from unrelated files proceed
        # concurrently, and the dentry flip below makes each write an
        # atomic whole-file replace (an inode without a dentry is
        # harmless garbage; a dentry without data would not be)
        ino = uuid.uuid4().hex
        await self.striper.write(self._file_oid(ino), data)
        async with self._mutate:
            parent, name, dentries = await self._parent_of(path)
            existing = dentries.get(name)
            if existing and existing["type"] == "dir":
                raise FsError(f"EISDIR: {path}")
            dentry = {"type": "file", "size": len(data),
                      "mtime": time.time(), "ino": ino}
            if existing:
                # overwrite keeps identity metadata (POSIX: writing
                # does not chown/chmod)
                for k in ("mode", "owner"):
                    if k in existing:
                        dentry[k] = existing[k]
            else:
                dentry["mode"] = 0o666
                if owner is not None:
                    dentry["owner"] = owner
            event = {"op": "set_dentry", "parent": parent, "name": name,
                     "dentry": dentry}
            if existing and existing.get("ino"):
                # the replaced inode's data is dropped in the same
                # journaled event (concurrent readers are excluded by the
                # caps layer: writes need the exclusive cap) — UNLESS a
                # snapshot pins it (COW: the snap keeps the old bytes)
                if existing["ino"] not in self._snap_inos(
                        await self._load_snaptable(use_cache=True)):
                    event["drop_old_ino"] = existing["ino"]
            await self._journal(event)
            await self._apply_event(event)
            await self._journal_applied()

    async def read_file(self, path: str) -> bytes:
        path = self._norm(path)
        parent, name, dentries = await self._parent_of(path)
        ent = dentries.get(name)
        if ent is None:
            raise FsError(f"ENOENT: {path}")
        if ent["type"] != "file":
            raise FsError(f"EISDIR: {path}")
        return await self.striper.read(self._file_oid(ent["ino"]))

    async def unlink(self, path: str) -> None:
        path = self._norm(path)
        async with self._mutate:
            parent, name, dentries = await self._parent_of(path)
            ent = dentries.get(name)
            if ent is None:
                raise FsError(f"ENOENT: {path}")
            event = {"op": "rm_dentry", "parent": parent, "name": name}
            if ent["type"] == "dir":
                children = await self._load_dir(path)
                if children:
                    raise FsError(f"ENOTEMPTY: {path}")
                event["rmdir"] = path
            elif ent["ino"] not in self._snap_inos(
                    await self._load_snaptable(use_cache=True)):
                event["drop_ino"] = ent["ino"]
            await self._journal(event)
            await self._apply_event(event)
            await self._journal_applied()

    async def rename(self, src: str, dst: str) -> None:
        """File rename is a dentry-only move (the inode id stays, so no
        data transfer and no window where the data exists twice).
        Directory rename additionally RE-KEYS the subtree's dirfrag
        objects — dirfrags are path-keyed here, so this is O(subtree)
        where the reference's inode-keyed layout is O(1); the whole
        re-key rides ONE journal event, so replay finishes a half-moved
        tree."""
        src, dst = self._norm(src), self._norm(dst)
        async with self._mutate:
            sparent, sname, sdentries = await self._parent_of(src)
            ent = sdentries.get(sname)
            if ent is None:
                raise FsError(f"ENOENT: {src}")
            if ent["type"] == "dir":
                await self._rename_dir_locked(src, dst, ent)
                return
            dparent, dname, ddentries = await self._parent_of(dst)
            if ddentries.get(dname, {}).get("type") == "dir":
                raise FsError(f"EISDIR: {dst}")
            if src == dst:
                return
            old_dst = (sdentries if dparent == sparent
                       else ddentries).get(dname)
            # one journal event covering the whole multi-object update:
            # set the destination dentry FIRST, then drop the source
            # (replay after a crash between the two completes the move;
            # worst case both dentries briefly exist, never neither — the
            # reference's EUpdate orders its metablob the same way)
            subs = [{"op": "set_dentry", "parent": dparent, "name": dname,
                     "dentry": ent},
                    {"op": "rm_dentry", "parent": sparent, "name": sname}]
            if (old_dst and old_dst.get("ino")
                    and old_dst["ino"] != ent.get("ino")
                    and old_dst["ino"] not in self._snap_inos(
                        await self._load_snaptable(use_cache=True))):
                subs.append({"op": "drop_ino", "ino": old_dst["ino"]})
            event = {"op": "rename", "events": subs}
            await self._journal(event)
            await self._apply_event(event)
            await self._journal_applied()

    # -- snapshots (reference src/mds/SnapServer.cc + SnapRealm COW) ---------
    #
    # The fresh-inode-per-write discipline makes file data naturally
    # copy-on-write: a snapshot is a frozen {relpath -> dentry} tree in
    # the snap table plus a liveness rule — an inode referenced by any
    # snapshot is never dropped by overwrite/unlink/rename.  Snapshots
    # are crash-consistent (callers flush their write-behind first; the
    # client does).  In multi-rank deployments every snap-table mutation
    # routes through rank 0, the reference's snapserver seat.

    SNAPS_OID = "mds_snaptable"

    async def _load_snaptable(self, use_cache: bool = False
                              ) -> Dict[str, Dict]:
        """The hot-path pinned-ino checks pass use_cache=True: with no
        snapshots (the common case) the cache is a dict-hit, not a
        meta-pool round-trip per mutation.  Cache coherence across
        FileSystem instances is the CLUSTER's job: MDSCluster snapshot
        ops run under an all-ranks barrier and invalidate every rank's
        cache (invalidate_snap_cache)."""
        if use_cache and self._snap_cache is not None:
            return self._snap_cache
        try:
            table = json.loads(await self.meta.read(self.SNAPS_OID))
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            table = {}
        self._snap_cache = table
        return table

    def invalidate_snap_cache(self) -> None:
        self._snap_cache = None

    async def _save_snaptable(self, table: Dict[str, Dict]) -> None:
        await self.meta.write_full(self.SNAPS_OID,
                                   json.dumps(table).encode())
        self._snap_cache = table

    @staticmethod
    def _snap_inos(table: Dict[str, Dict]) -> set:
        out = set()
        for snap in table.values():
            for ent in snap.get("tree", {}).values():
                if ent.get("ino"):
                    out.add(ent["ino"])
        return out

    async def _collect_tree(self, root: str) -> Dict[str, Dict]:
        """{relpath -> dentry} for the subtree at root ('' = root dir
        itself); dirs carry {"type": "dir"}, files keep ino/size."""
        tree: Dict[str, Dict] = {}

        async def rec(path: str, rel: str) -> None:
            dentries = await self._load_dir(path)
            if dentries is None:
                return
            for name, ent in dentries.items():
                r = f"{rel}/{name}" if rel else name
                tree[r] = dict(ent)
                if ent["type"] == "dir":
                    await rec(posixpath.join(path, name), r)

        await rec(root, "")
        return tree

    async def snap_create(self, root: str, name: str) -> None:
        async with self._mutate:
            await self._snap_create_locked(root, name)

    async def _snap_create_locked(self, root: str, name: str) -> None:
        """Body of snap_create, caller holds the mutation barrier —
        MDSCluster calls this holding EVERY rank's lock, so no rank can
        race a drop_old_ino decision against the table commit."""
        root = self._norm(root)
        if "|" in name or "/" in name or not name:
            raise FsError(f"EINVAL: bad snap name {name!r}")
        if await self._load_dir(root) is None:
            raise FsError(f"ENOENT: {root}")
        table = await self._load_snaptable()
        key = f"{root}|{name}"
        if key in table:
            raise FsError(f"EEXIST: snap {name} on {root}")
        tree = await self._collect_tree(root)
        event = {"op": "snap_create", "key": key, "root": root,
                 "name": name, "tree": tree,
                 "created": time.time()}
        await self._journal(event)
        await self._apply_event(event)
        await self._journal_applied()

    async def snap_delete(self, root: str, name: str) -> None:
        async with self._mutate:
            await self._snap_delete_locked(root, name)

    async def _snap_delete_locked(self, root: str, name: str) -> None:
        root = self._norm(root)
        table = await self._load_snaptable()
        key = f"{root}|{name}"
        snap = table.get(key)
        if snap is None:
            raise FsError(f"ENOENT: snap {name} on {root}")
        # reclaim inodes only this snapshot pins AND no live dentry
        # references.  Liveness is decided by a NAMESPACE-WIDE walk, not
        # the snapshot-time path: a rename since the snapshot moved the
        # dentry (possibly out of the subtree) while the inode stayed
        # live — a path-stat would misread it as dead and destroy the
        # live file's data
        others = {k: v for k, v in table.items() if k != key}
        pinned_elsewhere = self._snap_inos(others)
        candidates = {ent["ino"] for ent in snap.get("tree", {}).values()
                      if ent.get("ino")
                      and ent["ino"] not in pinned_elsewhere}
        if candidates:
            live = {ent.get("ino")
                    for ent in (await self._collect_tree("/")).values()
                    if ent.get("ino")}
            candidates -= live
        event = {"op": "snap_delete", "key": key,
                 "drop": sorted(candidates)}
        await self._journal(event)
        await self._apply_event(event)
        await self._journal_applied()

    async def snap_list(self, root: str) -> List[str]:
        root = self._norm(root)
        table = await self._load_snaptable()
        return sorted(v["name"] for k, v in table.items()
                      if v.get("root") == root)

    async def _snap_entry(self, root: str, name: str) -> Dict:
        table = await self._load_snaptable()
        snap = table.get(f"{self._norm(root)}|{name}")
        if snap is None:
            raise FsError(f"ENOENT: snap {name} on {root}")
        return snap

    async def listdir_snap(self, root: str, name: str,
                           rel: str = "") -> List[str]:
        snap = await self._snap_entry(root, name)
        rel = rel.strip("/")
        if rel:
            ent = snap.get("tree", {}).get(rel)
            if ent is None:
                raise FsError(f"ENOENT: {rel} in snap {name}")
            if ent["type"] != "dir":
                raise FsError(f"ENOTDIR: {rel}")
        prefix = f"{rel}/" if rel else ""
        out = set()
        for r in snap.get("tree", {}):
            if r.startswith(prefix) and r != rel:
                out.add(r[len(prefix):].split("/")[0])
        return sorted(out)

    async def read_snap_file(self, root: str, name: str,
                             rel: str) -> bytes:
        snap = await self._snap_entry(root, name)
        ent = snap.get("tree", {}).get(rel.strip("/"))
        if ent is None:
            raise FsError(f"ENOENT: {rel} in snap {name}")
        if ent["type"] != "file":
            raise FsError(f"EISDIR: {rel}")
        return await self.striper.read(self._file_oid(ent["ino"]))

    async def _rename_dir_locked(self, src: str, dst: str,
                                 ent: Dict) -> None:
        """Directory move (caller holds _mutate).  Guards: dst must not
        exist (no dir-over-dir replace), dst must not be inside src
        (EINVAL, the classic cycle), parents must exist.  The journal
        event carries the POST-STATE dirfrag contents (like every other
        event), so replay never re-reads live objects a later mkdir may
        have re-created."""
        if src == dst:
            return  # POSIX: same entry, success
        if is_under(dst, src):
            raise FsError(f"EINVAL: cannot move {src} into itself")
        dparent, dname, ddentries = await self._parent_of(dst)
        if dname in ddentries:
            raise FsError(f"EEXIST: {dst}")
        # post-state snapshot: rel dir path -> its dentries (root = ""),
        # collected in ONE walk (each dirfrag read exactly once while
        # the rank lock is held)
        frags: Dict[str, Dict] = {}

        async def collect(path: str, rel: str) -> None:
            dentries = dict(await self._load_dir(path) or {})
            frags[rel] = dentries
            for name, e in dentries.items():
                if e["type"] == "dir":
                    await collect(posixpath.join(path, name),
                                  f"{rel}/{name}" if rel else name)

        await collect(src, "")
        sparent = posixpath.dirname(src)
        sname = posixpath.basename(src)
        event = {"op": "rename_dir", "src": src, "dst": dst,
                 "frags": frags,
                 "sparent": sparent, "sname": sname,
                 "dparent": dparent, "dname": dname, "dentry": ent}
        await self._journal(event)
        await self._apply_event(event)
        await self._journal_applied()

    async def walk(self, path: str = "/") -> Dict:
        """Recursive tree dump (debugging/`ceph fs dump` role)."""
        path = self._norm(path)
        out: Dict = {}
        for name in await self.listdir(path):
            full = posixpath.join(path, name)
            st = await self.stat(full)
            if st["type"] == "dir":
                out[name] = await self.walk(full)
            else:
                out[name] = st.get("size", 0)
        return out


# -- client sessions + capabilities (reference src/mds/SessionMap.h,
#    src/mds/Locker.cc caps/lease machinery) ---------------------------------


def may_access(st: Optional[Dict], client: Optional[str],
               want: str, path: str = "") -> None:
    """THE permission check (reference Client::may_read/may_write/
    may_open), shared by the server's path ops, snapshot reads, and
    open_file: owner and unstamped entries pass; others need the
    other-class bit of the mode (default world-rw 0o666 — no umask
    model).  `st` None (absent file) passes: creation is allowed,
    parent-directory permissions are out of scope."""
    if st is None:
        return
    owner = st.get("owner")
    if owner is None or owner == client:
        return
    bits = int(st.get("mode", 0o666))
    if want == "r" and not bits & 0o004:
        raise FsError(f"EACCES: {path} not readable")
    if want == "w" and not bits & 0o002:
        raise FsError(f"EACCES: {path} not writable")


class CapConflict(FsError):
    """The cap is held by a live conflicting session (retry after the
    holder releases, acks the revoke, or its lease lapses)."""


class MDSSession:
    """One client's stateful session (reference Session): identity, a
    renewable lease, the caps it holds, and a revoke queue the client is
    expected to drain (ack) — exactly the contract CephFS clients follow."""

    def __init__(self, client: str, session_id: str, ttl: float):
        self.client = client
        self.session_id = session_id
        self.ttl = ttl
        self.renewed = time.monotonic()
        self.caps: Dict[str, str] = {}  # path -> "r" | "rw"
        self.revoked: List[str] = []  # paths the MDS wants back

    @property
    def expired(self) -> bool:
        return time.monotonic() - self.renewed > self.ttl

    def renew(self) -> List[str]:
        """Refresh the lease; returns (and clears) pending revokes — the
        client must stop using those paths and release_cap() them."""
        self.renewed = time.monotonic()
        out, self.revoked = self.revoked, []
        return out


class MDSServer:
    """Session/caps gatekeeper over a FileSystem (reference mds Server +
    Locker in miniature): clients open sessions, acquire read (shared) or
    rw (exclusive) capabilities per path, and operate through the server,
    which enforces that the needed cap is held and live.  Conflicting
    grants revoke the loser: live holders get the path queued on their
    revoke list and the requester is refused with CapConflict until the
    holder releases or its lease lapses (session autoclose role).

    Divergence by design: path-granular caps (the reference's are
    per-inode with Fw/Fr/Fx bit splits).  One MDSServer serves one
    RANK; multi-active deployments compose several through
    mds_cluster.MDSCluster, which owns subtree authority + migration."""

    def __init__(self, fs: FileSystem, session_timeout: float = 60.0):
        self.fs = fs
        self.session_timeout = session_timeout
        self.sessions: Dict[str, MDSSession] = {}
        # path -> {session_id: mode}
        self._caps: Dict[str, Dict[str, str]] = {}

    # -- session lifecycle ---------------------------------------------------

    def open_session(self, client: str) -> MDSSession:
        s = MDSSession(client, uuid.uuid4().hex, self.session_timeout)
        self.sessions[s.session_id] = s
        return s

    def close_session(self, session_id: str) -> None:
        s = self.sessions.pop(session_id, None)
        if s is None:
            return
        for path in list(s.caps):
            self._drop(path, session_id)

    def _evict_if_dead(self, session_id: str) -> bool:
        s = self.sessions.get(session_id)
        if s is None:
            return True
        if s.expired:
            self.close_session(session_id)  # autoclose: caps released
            return True
        return False

    def _drop(self, path: str, session_id: str) -> None:
        holders = self._caps.get(path)
        if holders:
            holders.pop(session_id, None)
            if not holders:
                self._caps.pop(path, None)
        s = self.sessions.get(session_id)
        if s:
            s.caps.pop(path, None)

    # -- capabilities --------------------------------------------------------

    def acquire_cap(self, session: MDSSession, path: str,
                    mode: str = "r") -> None:
        """Grant `mode` on `path` or raise CapConflict.  "r" caps are
        shared; "rw" is exclusive.  Conflicting live holders get the path
        queued for revoke (they learn at their next renew()); dead
        holders are evicted on the spot."""
        assert mode in ("r", "rw")
        if self._evict_if_dead(session.session_id):
            raise FsError("ESTALE: session expired")
        path = FileSystem._norm(path)
        conflict = False
        for sid, held in list(self._caps.get(path, {}).items()):
            if sid == session.session_id:
                continue
            if mode == "r" and held == "r":
                continue  # shared read
            if self._evict_if_dead(sid):
                continue
            # live conflicting holder: ask for the cap back, refuse now
            other = self.sessions[sid]
            if path not in other.revoked:
                other.revoked.append(path)
            conflict = True
        if conflict:
            raise CapConflict(f"EAGAIN: cap on {path} held elsewhere")
        # re-fetch AFTER evictions: evicting the last holder pops the
        # path's dict from _caps, and granting into the detached dict
        # would make the cap invisible to later conflict checks
        holders = self._caps.setdefault(path, {})
        holders[session.session_id] = mode
        session.caps[path] = mode
        if path in session.revoked:
            session.revoked.remove(path)  # fresh grant supersedes

    def release_cap(self, session: MDSSession, path: str) -> None:
        path = FileSystem._norm(path)
        self._drop(path, session.session_id)
        # releasing IS complying with a pending revoke: a later fresh
        # grant must not trip over the stale revocation marker
        if path in session.revoked:
            session.revoked.remove(path)

    def _require(self, session: MDSSession, path: str, mode: str) -> None:
        if self._evict_if_dead(session.session_id):
            raise FsError("ESTALE: session expired")
        path = FileSystem._norm(path)
        held = session.caps.get(path)
        if held is None or (mode == "rw" and held != "rw"):
            # implicit acquisition, as clients do on open
            self.acquire_cap(session, path, mode)
        elif path in session.revoked:
            raise FsError(f"ESTALE: cap on {path} revoked; renew first")

    # -- capped operations (the libcephfs-style surface) ---------------------

    async def write_file(self, session: MDSSession, path: str,
                         data: bytes) -> None:
        self._require(session, path, "rw")
        await self._may(session, path, "w")
        await self.fs.write_file(path, data, owner=session.client)

    async def read_file(self, session: MDSSession, path: str) -> bytes:
        self._require(session, path, "r")
        await self._may(session, path, "r")
        return await self.fs.read_file(path)

    async def mkdir(self, session: MDSSession, path: str) -> None:
        self._require(session, path, "rw")
        await self.fs.mkdir(path, owner=session.client)

    async def chmod(self, session: MDSSession, path: str,
                    mode: int) -> None:
        """Owner-gated permission update (POSIX chmod needs ownership,
        not write permission; files created before ownership stamping
        have no owner and stay mutable by anyone, like uid-0-less
        legacy data).  Ownership verifies INSIDE FileSystem.chmod
        under the mutation lock; here only session liveness."""
        if self._evict_if_dead(session.session_id):
            raise FsError("ESTALE: session expired")
        await self.fs.chmod(path, mode, requester=session.client)

    async def _may(self, session: MDSSession, path: str,
                   want: str) -> None:
        """Mode-bit check for the path-based surface: one shared rule
        (module-level may_access) for every enforcement point."""
        try:
            st = await self.fs.stat(path)
        except FsError:
            return
        may_access(st, session.client, want, path)

    async def unlink(self, session: MDSSession, path: str) -> None:
        self._require(session, path, "rw")
        await self.fs.unlink(path)
        self._drop(FileSystem._norm(path), session.session_id)

    def _revoke_subtree(self, root: str, keep_session: str) -> bool:
        """Queue revokes for every OTHER session's caps under `root`
        (directory rename must not strand caps naming dead paths);
        returns True if a live conflicting holder remains."""
        root = FileSystem._norm(root)
        conflict = False
        for path, holders in list(self._caps.items()):
            if not is_under(path, root):
                continue
            for sid in list(holders):
                if sid == keep_session:
                    continue
                if self._evict_if_dead(sid):
                    continue
                other = self.sessions[sid]
                if path not in other.revoked:
                    other.revoked.append(path)
                conflict = True
        return conflict

    async def rename(self, session: MDSSession, src: str, dst: str) -> None:
        self._require(session, src, "rw")
        self._require(session, dst, "rw")
        src_n, dst_n = FileSystem._norm(src), FileSystem._norm(dst)
        is_dir = False
        try:
            is_dir = (await self.fs.stat(src_n))["type"] == "dir"
        except FsError:
            pass
        if is_dir and self._revoke_subtree(src_n, session.session_id):
            # live holders under the moving tree must flush + release
            # first, or their write-behind would later flush into dead
            # paths (same compliance contract as subtree export)
            raise CapConflict(
                f"EAGAIN: caps under {src_n} held elsewhere")
        await self.fs.rename(src, dst)
        if is_dir:
            # every cap under either path now names a dead (or brand
            # new) path: drop them; clients re-acquire at the new paths
            for path in list(self._caps):
                if is_under(path, src_n) or is_under(path, dst_n):
                    for sid in list(self._caps.get(path, {})):
                        self._drop(path, sid)

    async def listdir(self, session: MDSSession, path: str) -> List[str]:
        self._require(session, path, "r")
        return await self.fs.listdir(path)

    async def stat(self, session: MDSSession, path: str) -> Dict:
        self._require(session, path, "r")
        return await self.fs.stat(path)

    # snapshots: creation is a metadata write on the root (rw); reads
    # are read-capped on the root, like the reference's .snap dirs
    async def snap_create(self, session: MDSSession, path: str,
                          name: str) -> None:
        self._require(session, path, "rw")
        await self.fs.snap_create(path, name)

    async def snap_delete(self, session: MDSSession, path: str,
                          name: str) -> None:
        self._require(session, path, "rw")
        await self.fs.snap_delete(path, name)

    async def snap_list(self, session: MDSSession, path: str) -> List[str]:
        self._require(session, path, "r")
        return await self.fs.snap_list(path)

    async def read_snap_file(self, session: MDSSession, path: str,
                             name: str, rel: str) -> bytes:
        self._require(session, path, "r")
        # the snapshot captured the file's mode/owner with its dentry:
        # a 0600 file's content must not leak through a snapshot of an
        # ancestor (r5 review bypass)
        snap = await self.fs._snap_entry(FileSystem._norm(path), name)
        may_access(snap.get("tree", {}).get(rel.strip("/")),
                   session.client, "r", f"{path}@{name}/{rel}")
        return await self.fs.read_snap_file(path, name, rel)

    async def listdir_snap(self, session: MDSSession, path: str,
                           name: str, rel: str = "") -> List[str]:
        self._require(session, path, "r")
        return await self.fs.listdir_snap(path, name, rel)


class CephFSClient:
    """The CLIENT half of the filesystem (reference src/client/Client.cc
    in miniature): a cap-aware cache over an MDSServer session.

    Capability semantics, as the reference client enforces them in its
    own cache (Client.cc Fc/Fb handling):
    - under a SHARED "r" cap, reads are cached locally and served
      without touching the MDS until the cap goes away;
    - under an EXCLUSIVE "rw" cap, writes are WRITE-BEHIND: they land in
      the local dirty cache and reach the MDS only on flush — revoke,
      release, fsync, or unmount;
    - a revoke (delivered on lease renewal, the reference's cap message
      flow) forces compliance before the conflicting client's grant can
      succeed: flush dirty bytes, drop the cache, release the cap.

    Coherence across clients therefore holds exactly because the server
    refuses a conflicting grant until the holder has complied — the
    writer's dirty data is visible to the next reader by construction.

    ``renew_interval`` piggybacks a lease renewal (and thus revoke
    processing) on client operations, so a busy client converges without
    a background thread; tests and embedders may call renew() directly.
    """

    def __init__(self, mds: MDSServer, client: str = "client",
                 renew_interval: float = 1.0):
        self.mds = mds
        self.client_name = client
        self.session = mds.open_session(client)
        self.renew_interval = renew_interval
        self._last_renew = time.monotonic()
        self._clean: Dict[str, bytes] = {}  # path -> cached file data
        self._dirty: Dict[str, bytes] = {}  # path -> write-behind data
        self.cache_hits = 0
        self.flushes = 0

    # -- cap compliance ------------------------------------------------------

    async def renew(self) -> None:
        """Renew the lease and COMPLY with pending revokes: flush dirty
        data, drop the cache, release the cap — the contract that lets
        the MDS grant the path to the conflicting client."""
        for path in self.mds.sessions.get(
                self.session.session_id, self.session).renew():
            await self._flush_path(path)
            self._clean.pop(path, None)
            self.mds.release_cap(self.session, path)

    async def _maybe_renew(self) -> None:
        if time.monotonic() - self._last_renew >= self.renew_interval:
            self._last_renew = time.monotonic()
            await self.renew()

    async def _flush_path(self, path: str) -> None:
        data = self._dirty.pop(path, None)
        if data is not None:
            self.flushes += 1
            await self.mds.write_file(self.session, path, data)
            self._clean[path] = data

    async def _acquire(self, path: str, mode: str,
                       retries: int = 20, delay: float = 0.05) -> None:
        """Acquire with revoke-processing retries: a CapConflict means a
        live holder was asked to give the cap back — renew (processing
        OUR revokes too) and retry while the holder complies."""
        for attempt in range(retries):
            try:
                self.mds.acquire_cap(self.session, path, mode)
                return
            except CapConflict:
                await self.renew()
                if attempt == retries - 1:
                    raise
                await asyncio.sleep(delay)

    # -- file surface (libcephfs role) ---------------------------------------

    async def write(self, path: str, data: bytes) -> None:
        await self._maybe_renew()
        held = self.session.caps.get(FileSystem._norm(path))
        if held != "rw":
            await self._acquire(path, "rw")
        # write-behind under the exclusive cap: bytes stay local
        self._dirty[FileSystem._norm(path)] = bytes(data)

    async def read(self, path: str) -> bytes:
        # whole-file read = positional read of everything: ONE copy of
        # the dirty/clean/server tier logic (_image), one counter
        return await self.pread(path, 0, -1)

    async def fsync(self, path: str) -> None:
        # process pending revokes FIRST: flushing a path whose cap was
        # revoked would ESTALE mid-flush (renew() both complies and
        # flushes revoked paths, so the dirty bytes land either way)
        await self.renew()
        await self._flush_path(FileSystem._norm(path))

    async def mkdir(self, path: str) -> None:
        await self._maybe_renew()
        await self.mds.mkdir(self.session, path)

    async def listdir(self, path: str) -> List[str]:
        await self._maybe_renew()
        # a fresh listing must see peers' flushed creates: dir listings
        # are not cached (the reference caches dentries under Fs caps;
        # path-granular caps make that a follow-up, not a default)
        return await self.mds.listdir(self.session, path)

    async def stat(self, path: str) -> Dict:
        await self._maybe_renew()
        p = FileSystem._norm(path)
        if p in self._dirty:
            return {"type": "file", "size": len(self._dirty[p])}
        return await self.mds.stat(self.session, path)

    async def unlink(self, path: str) -> None:
        await self._maybe_renew()
        p = FileSystem._norm(path)
        self._dirty.pop(p, None)
        self._clean.pop(p, None)
        await self._acquire(path, "rw")
        await self.mds.unlink(self.session, path)

    async def rename(self, path: str, dst: str) -> None:
        """Rename through the server (cap-checked; directory renames
        force other holders under the tree to comply first).  The local
        cache entries under BOTH paths are purged — they name dead
        paths afterwards."""
        s, d = FileSystem._norm(path), FileSystem._norm(dst)
        await self.renew()
        # our own write-behind under the source tree must land first:
        # it flushes by OLD path, which is only writable pre-rename
        for dirty in list(self._dirty):
            if is_under(dirty, s):
                await self._flush_path(dirty)
        # few internal retries only: OTHER holders comply through THEIR
        # renewals, which an embedding facade drives between ITS retries
        # — spinning here would just delay that outer loop
        for attempt in range(3):
            try:
                await self.mds.rename(self.session, s, d)
                break
            except CapConflict:
                await self.renew()
                if attempt == 2:
                    raise
                await asyncio.sleep(0.02)
        for cache in (self._dirty, self._clean):
            for p in list(cache):
                if is_under(p, s) or is_under(p, d):
                    cache.pop(p, None)
        for p in list(self.session.caps):
            if is_under(p, s) or is_under(p, d):
                self.mds.release_cap(self.session, p)

    # -- snapshots -----------------------------------------------------------

    async def snap_create(self, path: str, name: str) -> None:
        """Snapshot the subtree at `path`.  The client's own
        write-behind bytes under the subtree are flushed FIRST, so the
        snapshot captures them (crash consistency is only as good as
        what has reached the MDS)."""
        await self.renew()
        p = FileSystem._norm(path)
        for dirty in list(self._dirty):
            if is_under(dirty, p):
                await self._flush_path(dirty)
        await self.mds.snap_create(self.session, path, name)

    async def snap_delete(self, path: str, name: str) -> None:
        await self._maybe_renew()
        await self.mds.snap_delete(self.session, path, name)

    async def snap_list(self, path: str) -> List[str]:
        await self._maybe_renew()
        return await self.mds.snap_list(self.session, path)

    async def read_snap(self, path: str, name: str, rel: str) -> bytes:
        await self._maybe_renew()
        # same conflict-retry discipline as every capped op: the
        # implicit "r" acquisition on the snap root may need a holder
        # to comply first
        for attempt in range(20):
            try:
                return await self.mds.read_snap_file(
                    self.session, path, name, rel)
            except CapConflict:
                await self.renew()
                if attempt == 19:
                    raise
                await asyncio.sleep(0.05)

    async def listdir_snap(self, path: str, name: str,
                           rel: str = "") -> List[str]:
        await self._maybe_renew()
        return await self.mds.listdir_snap(self.session, path, name, rel)

    # -- positional I/O (the ll_read/ll_write substrate for handles) ---------

    async def _image(self, p: str, create: bool = False) -> bytes:
        """The file's current image through the cache tiers (the ONE
        copy of the dirty/clean/server resolution — read() and the
        positional ops all ride it): our own write-behind bytes, the
        clean cache, else the server.  ENOENT raises unless `create`
        (the write path treats a missing file as empty)."""
        if p in self._dirty:
            self.cache_hits += 1
            return self._dirty[p]
        if p in self._clean and self.session.caps.get(p):
            self.cache_hits += 1
            return self._clean[p]
        try:
            data = await self.mds.read_file(self.session, p)
        except FsError as e:
            if create and "ENOENT" in str(e):
                return b""
            raise
        self._clean[p] = data
        return data

    async def _image_capped(self, p: str, mode: str,
                            create: bool = False) -> bytes:
        """Acquire (or upgrade to) `mode` and resolve the image.  A
        permission denial RELEASES the cap acquired for this very op —
        a denied client squatting an exclusive cap would wedge every
        authorized client behind a revoke it has no reason to answer."""
        had = self.session.caps.get(p)
        need = (had is None) if mode == "r" else (had != "rw")
        if need and not (mode == "r" and p in self._dirty):
            await self._acquire(p, mode)
        try:
            if mode == "rw":
                # write permission checks UP FRONT, not at flush time:
                # a denied write surfacing later from renew() would
                # have already dropped the dirty bytes and left this
                # client squatting the exclusive cap (r5 review repro)
                await self.mds._may(self.session, p, "w")
            return await self._image(p, create=create)
        except FsError as e:
            if "EACCES" in str(e) and had != self.session.caps.get(p):
                self.mds.release_cap(self.session, p)
            raise

    async def pread(self, path: str, off: int, n: int = -1) -> bytes:
        await self._maybe_renew()
        p = FileSystem._norm(path)
        data = await self._image_capped(p, "r")
        return data[off:] if n < 0 else data[off:off + n]

    async def pwrite(self, path: str, off: int, data: bytes) -> int:
        """Positional write-behind: splice `data` at `off` over the
        current image (zero-extending a hole), dirty under the
        exclusive cap (Client::_write role)."""
        await self._maybe_renew()
        p = FileSystem._norm(path)
        buf = bytearray(await self._image_capped(p, "rw", create=True))
        if len(buf) < off:
            buf.extend(b"\x00" * (off - len(buf)))
        buf[off:off + len(data)] = data
        self._dirty[p] = bytes(buf)
        return len(data)

    async def append(self, path: str, data: bytes) -> int:
        """O_APPEND write: EOF resolves and the splice lands in ONE
        step under the exclusive cap, so a concurrent client cannot
        slip an append between a stat and a pwrite.  Returns the
        offset the data landed at."""
        await self._maybe_renew()
        p = FileSystem._norm(path)
        buf = bytearray(await self._image_capped(p, "rw", create=True))
        off = len(buf)
        buf.extend(data)
        self._dirty[p] = bytes(buf)
        return off

    async def truncate(self, path: str, size: int) -> None:
        await self._maybe_renew()
        p = FileSystem._norm(path)
        buf = bytearray(await self._image_capped(p, "rw", create=True))
        if len(buf) < size:
            buf.extend(b"\x00" * (size - len(buf)))
        else:
            del buf[size:]
        self._dirty[p] = bytes(buf)

    async def chmod(self, path: str, mode: int) -> None:
        await self._maybe_renew()
        # our own write-behind must land first: the file may exist only
        # in the dirty cache, and FileSystem.chmod stats the server
        await self._flush_path(FileSystem._norm(path))
        await self.mds.chmod(self.session, path, mode)

    async def open(self, path: str, mode: str = "r") -> "CephFSFile":
        return await open_file(self, path, mode)

    async def unmount(self) -> None:
        """Flush every dirty file, release every cap, close the session
        (the reference client's unmount barrier)."""
        await self.renew()  # comply with pending revokes before flushing
        for path in list(self._dirty):
            await self._flush_path(path)
        self._clean.clear()
        self.mds.close_session(self.session.session_id)


# -- file handles (libcephfs ll_open/ll_read/ll_write/ll_fsync role) ---------


async def open_file(io, path: str, mode: str = "r") -> "CephFSFile":
    """Open a handle on `io` (a CephFSClient or any facade exposing the
    same pread/pwrite/truncate/stat/fsync surface).  Modes follow the
    POSIX open flags the reference's ll_open honors:

      r   read-only, must exist (O_RDONLY)
      r+  read/write, must exist (O_RDWR)
      w   write-only, create or TRUNCATE (O_WRONLY|O_CREAT|O_TRUNC)
      a   write-only append, create if missing (O_WRONLY|O_CREAT|O_APPEND)

    Permission checks happen HERE (EISDIR on directories, ENOENT for
    must-exist modes) and per-op (EBADF for the wrong direction on a
    one-way handle) — cap acquisition rides the first read/write, per
    handle direction."""
    if mode not in ("r", "r+", "w", "a"):
        raise FsError(f"EINVAL: bad open mode {mode!r}")
    p = FileSystem._norm(path)
    st = None
    last: Optional[FsError] = None
    for _attempt in range(50):
        try:
            st = await io.stat(p)
            break
        except FsError as e:
            if "ENOENT" in str(e):
                st = None
                break
            if "EAGAIN" not in str(e) and "ESTALE" not in str(e):
                raise
            # a conflicting holder was asked for the cap back: drive
            # our own revoke compliance and retry while it complies
            # (the same loop every capped client op runs)
            last = e
            renew = getattr(io, "renew_all", None) or getattr(
                io, "renew", None)
            if renew is not None:
                await renew()
            await asyncio.sleep(0.05)
    else:
        raise last if last is not None else FsError(f"EAGAIN: {p}")
    if st is not None and st.get("type") == "dir":
        raise FsError(f"EISDIR: {p}")
    if st is None and mode in ("r", "r+"):
        raise FsError(f"ENOENT: {p}")
    # permission bits: the ONE shared check (may_access) against the
    # open direction(s)
    me = getattr(io, "client_name", None)
    if mode in ("r", "r+"):
        may_access(st, me, "r", p)
    if mode in ("r+", "w", "a"):
        may_access(st, me, "w", p)
    fh = CephFSFile(io, p, mode)
    if mode == "w":
        # O_TRUNC|O_CREAT: the handle starts from an empty image (a
        # close with no writes still creates the empty file)
        await io.truncate(p, 0)
    elif mode == "a" and st is None:
        await io.truncate(p, 0)  # O_CREAT
    return fh


class CephFSFile:
    """An open file handle (reference Fh, src/client/Client.cc
    ll_read/ll_write semantics): per-handle mode enforcement, a
    sequential offset for read()/write(), positional pread/pwrite, and
    O_APPEND writes landing at the current EOF.  Data rides the owning
    client's cap-aware write-behind cache, so a revoke mid-write
    flushes and the next operation transparently re-acquires."""

    def __init__(self, io, path: str, mode: str):
        self._io = io
        self.path = path
        self.mode = mode
        self.offset = 0
        self.closed = False

    def _check(self, want: str) -> None:
        if self.closed:
            raise FsError(f"EBADF: {self.path} handle closed")
        if want == "r" and self.mode in ("w", "a"):
            raise FsError(f"EBADF: {self.path} not open for read")
        if want == "w" and self.mode == "r":
            raise FsError(f"EBADF: {self.path} not open for write")

    async def pread(self, off: int, n: int = -1) -> bytes:
        self._check("r")
        return await self._io.pread(self.path, off, n)

    async def pwrite(self, off: int, data: bytes) -> int:
        self._check("w")
        return await self._io.pwrite(self.path, off, data)

    async def read(self, n: int = -1) -> bytes:
        self._check("r")
        data = await self._io.pread(self.path, self.offset, n)
        self.offset += len(data)
        return data

    async def write(self, data: bytes) -> int:
        self._check("w")
        if self.mode == "a":
            # O_APPEND: EOF resolution and splice are ONE operation
            # under the exclusive cap (io.append) — a stat-then-pwrite
            # pair would let a concurrent append slip in between
            off = await self._io.append(self.path, data)
            self.offset = off + len(data)
            return len(data)
        n = await self._io.pwrite(self.path, self.offset, data)
        self.offset += n
        return n

    async def truncate(self, size: int) -> None:
        self._check("w")
        await self._io.truncate(self.path, size)

    async def fsync(self) -> None:
        if self.closed:
            raise FsError(f"EBADF: {self.path} handle closed")
        await self._io.fsync(self.path)

    async def close(self) -> None:
        """Flush on close (the reference's ll_release -> _flush): the
        handle's writes are durable at the MDS once close returns."""
        if self.closed:
            return
        self.closed = True
        if self.mode != "r":
            await self._io.fsync(self.path)

    async def __aenter__(self) -> "CephFSFile":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()
