"""MDS-lite: a POSIX-style file namespace over RADOS.

Role-equivalent of the reference's CephFS metadata path in miniature
(reference src/mds/, src/client/): directories are metadata objects
holding dentries (the CInode/CDir/CDentry cache's persistent form —
reference stores dirfrags as omap on meta-pool objects); file data is
striped over data-pool objects exactly like the reference's
``<ino>.<frag>`` layout (via the striper).  The API mirrors libcephfs's
shape: mkdir/listdir/stat/write/read/unlink/rename.

Divergence by design: a single MDS with no journaling/subtree migration —
the namespace-over-objects layout and path-walk semantics are the core
being reproduced; locking rides the cls lock class when callers need it.
"""

from __future__ import annotations

import json
import posixpath
import time
import uuid
from typing import Dict, List, Optional

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx
from ceph_tpu.rados.striper import RadosStriper


class FsError(Exception):
    pass


class FileSystem:
    def __init__(self, meta_ioctx: IoCtx, data_ioctx: Optional[IoCtx] = None,
                 object_size: int = 1 << 22):
        self.meta = meta_ioctx
        self.data = data_ioctx or meta_ioctx
        self.striper = RadosStriper(self.data, object_size=object_size)

    # -- dentries ------------------------------------------------------------

    @staticmethod
    def _norm(path: str) -> str:
        p = posixpath.normpath("/" + path.strip("/"))
        return p

    @staticmethod
    def _dir_oid(path: str) -> str:
        return f"dir:{path}"

    @staticmethod
    def _file_oid(ino: str) -> str:
        # data rides an IMMUTABLE inode id (the reference's <ino>.<frag>
        # layout), so rename never touches data objects
        return f"ino:{ino}"

    async def _load_dir(self, path: str) -> Optional[Dict[str, Dict]]:
        try:
            return json.loads(await self.meta.read(self._dir_oid(path)))
        except RadosError:
            return None

    async def _save_dir(self, path: str, dentries: Dict[str, Dict]) -> None:
        await self.meta.write_full(self._dir_oid(path),
                                   json.dumps(dentries).encode())

    async def mkfs(self) -> None:
        if await self._load_dir("/") is None:
            await self._save_dir("/", {})

    async def _parent_of(self, path: str):
        parent = posixpath.dirname(path)
        name = posixpath.basename(path)
        dentries = await self._load_dir(parent)
        if dentries is None:
            raise FsError(f"ENOENT: parent {parent}")
        return parent, name, dentries

    # -- namespace ops -------------------------------------------------------

    async def mkdir(self, path: str) -> None:
        path = self._norm(path)
        if path == "/":
            raise FsError("EEXIST: /")
        parent, name, dentries = await self._parent_of(path)
        if name in dentries:
            raise FsError(f"EEXIST: {path}")
        await self._save_dir(path, {})
        dentries[name] = {"type": "dir", "mtime": time.time()}
        await self._save_dir(parent, dentries)

    async def listdir(self, path: str) -> List[str]:
        path = self._norm(path)
        dentries = await self._load_dir(path)
        if dentries is None:
            raise FsError(f"ENOENT: {path}")
        return sorted(dentries)

    async def stat(self, path: str) -> Dict:
        path = self._norm(path)
        if path == "/":
            return {"type": "dir"}
        parent, name, dentries = await self._parent_of(path)
        if name not in dentries:
            raise FsError(f"ENOENT: {path}")
        return dict(dentries[name])

    async def write_file(self, path: str, data: bytes) -> None:
        path = self._norm(path)
        parent, name, dentries = await self._parent_of(path)
        existing = dentries.get(name)
        if existing and existing["type"] == "dir":
            raise FsError(f"EISDIR: {path}")
        ino = (existing or {}).get("ino") or uuid.uuid4().hex
        await self.striper.write(self._file_oid(ino), data)
        dentries[name] = {"type": "file", "size": len(data),
                          "mtime": time.time(), "ino": ino}
        await self._save_dir(parent, dentries)

    async def read_file(self, path: str) -> bytes:
        path = self._norm(path)
        parent, name, dentries = await self._parent_of(path)
        ent = dentries.get(name)
        if ent is None:
            raise FsError(f"ENOENT: {path}")
        if ent["type"] != "file":
            raise FsError(f"EISDIR: {path}")
        return await self.striper.read(self._file_oid(ent["ino"]))

    async def unlink(self, path: str) -> None:
        path = self._norm(path)
        parent, name, dentries = await self._parent_of(path)
        ent = dentries.get(name)
        if ent is None:
            raise FsError(f"ENOENT: {path}")
        if ent["type"] == "dir":
            children = await self._load_dir(path)
            if children:
                raise FsError(f"ENOTEMPTY: {path}")
            try:
                await self.meta.remove(self._dir_oid(path))
            except RadosError:
                pass
        else:
            await self.striper.remove(self._file_oid(ent["ino"]))
        del dentries[name]
        await self._save_dir(parent, dentries)

    async def rename(self, src: str, dst: str) -> None:
        """Dentry-only move: the inode id stays, so no data transfer and
        no window where the data exists twice."""
        src, dst = self._norm(src), self._norm(dst)
        sparent, sname, sdentries = await self._parent_of(src)
        ent = sdentries.get(sname)
        if ent is None:
            raise FsError(f"ENOENT: {src}")
        if ent["type"] == "dir":
            raise FsError("EINVAL: dir rename unsupported in mds-lite")
        dparent, dname, ddentries = await self._parent_of(dst)
        if ddentries.get(dname, {}).get("type") == "dir":
            raise FsError(f"EISDIR: {dst}")
        if src == dst:
            return
        if dparent == sparent:
            old_dst = sdentries.get(dname)
            sdentries[dname] = ent
            del sdentries[sname]
            await self._save_dir(sparent, sdentries)
        else:
            old_dst = ddentries.get(dname)
            ddentries[dname] = ent
            await self._save_dir(dparent, ddentries)
            del sdentries[sname]
            await self._save_dir(sparent, sdentries)
        # an overwritten destination file's data objects are unreferenced
        if old_dst and old_dst.get("ino") and old_dst["ino"] != ent.get("ino"):
            await self.striper.remove(self._file_oid(old_dst["ino"]))

    async def walk(self, path: str = "/") -> Dict:
        """Recursive tree dump (debugging/`ceph fs dump` role)."""
        path = self._norm(path)
        out: Dict = {}
        for name in await self.listdir(path):
            full = posixpath.join(path, name)
            st = await self.stat(full)
            if st["type"] == "dir":
                out[name] = await self.walk(full)
            else:
                out[name] = st.get("size", 0)
        return out
