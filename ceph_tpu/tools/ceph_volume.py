"""ceph-volume-lite: OSD data-directory preparation and inventory
(reference src/ceph-volume: `ceph-volume lvm prepare/activate/list` and
`ceph-volume inventory`, translated from LVM/block devices to the
directory-backed BlueStore this framework's daemons mount).

    python -m ceph_tpu.tools.ceph_volume inventory --base DIR
    python -m ceph_tpu.tools.ceph_volume prepare --base DIR --osd-id N
    python -m ceph_tpu.tools.ceph_volume list --base DIR
    python -m ceph_tpu.tools.ceph_volume zap --base DIR --osd-id N --yes

prepare lays down the BlueStore on-disk shape (block file + KV WAL dir)
plus the osd_fsid/whoami stamp files the reference writes, so a daemon
host (tools/cephadm.py) can adopt the directory; activate is implicit in
daemon start, exactly as cephadm drives it.  list/inventory read the
stamps back; zap destroys a prepared directory (name + --yes guard)."""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import uuid

STAMP = "osd_stamp.json"


def _osd_dir(base: str, osd_id: int) -> str:
    return os.path.join(base, f"osd.{osd_id}")


def prepare(args) -> int:
    path = _osd_dir(args.base, args.osd_id)
    if os.path.exists(os.path.join(path, STAMP)):
        print(f"osd.{args.osd_id} already prepared at {path}",
              file=sys.stderr)
        return 1
    # the BlueStore on-disk shape (bluestore.py expects block + db/)
    from ceph_tpu.rados.bluestore import BlueStore

    store = BlueStore(path, conf={})
    store.close()
    stamp = {"osd_id": args.osd_id, "osd_fsid": uuid.uuid4().hex,
             "type": "bluestore", "objectstore": "bluestore-lite"}
    with open(os.path.join(path, STAMP), "w") as f:
        json.dump(stamp, f, indent=1)
    print(f"prepared osd.{args.osd_id} fsid {stamp['osd_fsid']} at {path}")
    return 0


def _entries(base: str):
    if not os.path.isdir(base):
        return
    for name in sorted(os.listdir(base)):
        spath = os.path.join(base, name, STAMP)
        if name.startswith("osd.") and os.path.exists(spath):
            with open(spath) as f:
                stamp = json.load(f)
            stamp["path"] = os.path.join(base, name)
            yield stamp


def list_osds(args) -> int:
    out = list(_entries(args.base))
    print(json.dumps(out, indent=1))
    return 0


def inventory(args) -> int:
    """Directory inventory (ceph-volume inventory role): every candidate
    subdirectory, whether it holds a prepared OSD, and its usage."""
    rows = []
    prepared = {e["path"]: e for e in _entries(args.base)}
    if os.path.isdir(args.base):
        for name in sorted(os.listdir(args.base)):
            path = os.path.join(args.base, name)
            if not os.path.isdir(path):
                continue
            stamp = prepared.get(path)
            size = 0
            for root, _dirs, files in os.walk(path):
                size += sum(os.path.getsize(os.path.join(root, fn))
                            for fn in files)
            rows.append({
                "path": path,
                "available": stamp is None,
                "osd_id": stamp["osd_id"] if stamp else None,
                "osd_fsid": stamp["osd_fsid"] if stamp else None,
                "bytes_used": size,
            })
    print(json.dumps(rows, indent=1))
    return 0


def zap(args) -> int:
    path = _osd_dir(args.base, args.osd_id)
    if not os.path.exists(os.path.join(path, STAMP)):
        print(f"no prepared osd.{args.osd_id} at {path}", file=sys.stderr)
        return 1
    if not args.yes:
        print("zap destroys the OSD's data; pass --yes to confirm",
              file=sys.stderr)
        return 1
    shutil.rmtree(path)
    print(f"zapped osd.{args.osd_id} at {path}")
    return 0


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="ceph-volume-lite")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("prepare", "zap"):
        s = sub.add_parser(name)
        s.add_argument("--base", required=True)
        s.add_argument("--osd-id", type=int, required=True)
        if name == "zap":
            s.add_argument("--yes", action="store_true")
    for name in ("list", "inventory"):
        s = sub.add_parser(name)
        s.add_argument("--base", required=True)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    try:
        return {"prepare": prepare, "list": list_osds,
                "inventory": inventory, "zap": zap}[args.cmd](args)
    except BrokenPipeError:
        return 0  # downstream pager/head closed the pipe mid-print


if __name__ == "__main__":
    sys.exit(main())
