"""The ``ceph`` status command (reference src/ceph.in): cluster-state
queries over the mon-distributed maps and the client's admin fan-outs.

    python -m ceph_tpu.tools.ceph --mon HOST:PORT status
    python -m ceph_tpu.tools.ceph --mon HOST:PORT health
    python -m ceph_tpu.tools.ceph --mon HOST:PORT osd tree
    python -m ceph_tpu.tools.ceph --mon HOST:PORT pg dump
    python -m ceph_tpu.tools.ceph --mon HOST:PORT df

Everything derives from the same sources the reference CLI reads: the
OSDMap (epoch, OSD up/in states, pools, crush) fetched from the mon
quorum, per-PG acting sets computed client-side exactly as the data path
computes them (holes = degraded), and object counts via the paginated
per-PG listing fan-out (`pgls`, the scalable listing discipline).
``--format json`` emits machine-readable output; default is the
reference's human layout in miniature.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from typing import Dict, List


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="ceph cluster status tool")
    p.add_argument("--mon", help="mon address host:port (not needed for "
                                 "`daemon ASOK CMD`)")
    p.add_argument("--format", choices=("plain", "json"), default="plain")
    p.add_argument("--yes-i-really-really-mean-it", action="store_true",
                   dest="confirm_destroy",
                   help="required acknowledgement for `osd pool rm`")
    p.add_argument("-w", "--watch", action="store_true",
                   help="subscribe to the cluster log and stream new "
                        "entries (the `ceph -w` follow mode)")
    p.add_argument("--watch-channel", default="",
                   help="-w: only this channel (cluster, audit, ...)")
    p.add_argument("--watch-level", default="",
                   help="-w: minimum priority (debug/info/warn/error)")
    p.add_argument("--run-for", type=float, default=0.0,
                   help="-w: stop after this many seconds (0 = forever)")
    p.add_argument("words", nargs="*",
                   help="status | health [detail] | "
                        "health mute CHECK [TTL] | health unmute CHECK | "
                        "log last [N] [LEVEL] [CHANNEL] | "
                        "crash ls | crash info ID | crash archive ID | "
                        "crash archive-all | crash prune KEEP_DAYS | "
                        "tell TARGET CMD [k=v...] | "
                        "df | osd df | osd tree | pg dump | "
                        "pg scrub PGID | pg repair PGID | "
                        "osd out ID... | osd in ID... | "
                        "osd reweight ID W | osd crush reweight osd.ID W | "
                        "osd crush add-bucket NAME TYPE [ROOT] | "
                        "osd crush add|set osd.N W [BUCKET] | "
                        "osd crush move NAME BUCKET | osd crush rm NAME | "
                        "osd safe-to-destroy ID... | osd ok-to-stop ID... | "
                        "osd purge ID | "
                        "osd set-nearfull-ratio R | "
                        "osd set-backfillfull-ratio R | "
                        "osd set-full-ratio R | "
                        "osd pool ls | osd pool create NAME [k=v...] | "
                        "osd pool set NAME KEY VALUE | "
                        "osd pool rm NAME NAME --yes-i-really-really-mean-it"
                        " | daemon ASOK_PATH CMD [k=v...]")
    args = p.parse_args(argv)
    if not args.words and not args.watch:
        p.error("a command (or -w) is required")
    return args


def render_op_queue(dump: Dict) -> List[str]:
    """Render a daemon's ``dump_op_queue`` answer (scheduler.py
    ShardedOpQueue.dump + the OSD's admission-tracker view): per-shard
    per-class/per-client depths and current dmClock tags, then the
    over-limit ranking the saturation shed uses.  Pure so tests can pin
    the layout."""
    lines = [f"{dump.get('scheduler', '?')}: depth {dump.get('depth', 0)}"
             f", {dump.get('qos_clients', 0)} client states"]

    def tag(v) -> str:
        return "-" if v is None else f"{v:+.3f}"

    for sh in dump.get("shards", []):
        lines.append(f"  shard {sh.get('shard')}: depth {sh.get('depth', 0)}"
                     f" (strict {sh.get('strict', 0)})")
        for kind in ("classes", "clients"):
            for name, c in sorted((sh.get(kind) or {}).items()):
                lines.append(
                    f"    {'client ' if kind == 'clients' else ''}"
                    f"{name:<24} depth {c['depth']:<4} "
                    f"r/w/l {c['reservation']:g}/{c['weight']:g}/"
                    f"{c['limit']:g}  tags r {tag(c['r_tag'])} "
                    f"p {tag(c['p_tag'])} l {tag(c['l_tag'])}")
    admission = dump.get("admission") or {}
    if admission:
        lines.append("  admission (over-limit ranking):")
        ranked = sorted(admission.items(),
                        key=lambda kv: -kv[1].get("excess_s", 0.0))
        for name, st in ranked[:16]:
            lines.append(f"    {name:<24} limit {st.get('limit', 0):g}  "
                         f"excess {st.get('excess_s', 0.0):+.3f}s  "
                         f"idle {st.get('idle_s', 0.0):.1f}s")
        if len(ranked) > 16:
            lines.append(f"    ... {len(ranked) - 16} more clients")
    return lines


def render_reactors(dump: Dict) -> List[str]:
    """Render a daemon's ``dump_reactors`` answer (messenger
    dump_reactors: reactor worker shards, per-peer lane groups, and
    colocated rings).  Pure so tests can pin the layout."""
    mode = dump.get("reactor_mode", "thread")
    lines = [f"wire plane: {dump.get('op_threads', 0)} reactor workers "
             f"({mode} mode), "
             f"{dump.get('lanes_per_peer', 1)} lanes/peer, colocated ring "
             f"{'on' if dump.get('colocated_ring') else 'off'}"]
    workers = dump.get("workers") or []
    if workers:
        lines.append("  reactors:")
        for w in workers:
            if w.get("mode") == "process":
                # process-sharded worker: pid + the shm counter block
                lines.append(
                    f"    worker {w.get('id')} pid {w.get('pid')} "
                    f"{'up' if w.get('alive') else 'DEAD'}: conns "
                    f"{w.get('conns', 0)} (accepted "
                    f"{w.get('accepted', 0)}), rx_frames "
                    f"{w.get('rx_frames', 0)}, tx {w.get('tx_bytes', 0)}B"
                    + (f", respawns {w.get('respawns')}"
                       if w.get("respawns") else ""))
                continue
            lines.append(
                f"    worker {w.get('id')}: sockets {w.get('sockets', 0)} "
                f"(accepted {w.get('accepted', 0)}, dialed "
                f"{w.get('dialed', 0)})  rx_msgs {w.get('rx_msgs', 0)}")
    for peer in dump.get("peers") or []:
        host, port = (peer.get("peer") or ["?", 0])[:2]
        lines.append(
            f"  peer {host}:{port} group {peer.get('group', '')[:8]} "
            f"({'out' if peer.get('outbound') else 'in'}): "
            f"{peer.get('n_lanes', 0)} lanes, tx_gseq "
            f"{peer.get('tx_gseq', 0)}, rx parked {peer.get('rx_parked', 0)}"
            f", reassembling {peer.get('reassembling', 0)}")
        for ln in peer.get("lanes") or []:
            if ln.get("state") == "absent":
                lines.append(f"    lane {ln.get('lane')}: absent")
                continue
            role = "ctl " if ln.get("control") else "data"
            reactor = ln.get("reactor")
            shm = ln.get("shm")
            lines.append(
                f"    lane {ln.get('lane')} [{role}] {ln.get('state')}: "
                f"outbox {ln.get('outbox_frames', 0)}f/"
                f"{ln.get('outbox_bytes', 0)}B  unacked "
                f"{ln.get('unacked', 0)}  seq {ln.get('out_seq', 0)}/"
                f"{ln.get('in_seq', 0)}"
                + (f"  reactor {reactor}" if reactor is not None else "")
                + (f"  shm worker pid {shm.get('worker_pid')} ring "
                   f"tx {shm.get('tx_ring_fill', 0)}B/"
                   f"rx {shm.get('rx_ring_fill', 0)}B"
                   if shm else ""))
    for ring in dump.get("rings") or []:
        host, port = (ring.get("peer") or ["?", 0])[:2]
        lines.append(f"  ring {host}:{port} ({ring.get('peer_name', '')}): "
                     f"depth rx {ring.get('rx_depth', 0)} / tx "
                     f"{ring.get('tx_depth', 0)}"
                     + (" closed" if ring.get("closed") else ""))
    return lines


def render_log_dump(entries: List[Dict]) -> List[str]:
    """Render an asok ``log dump`` / ``log dump_recent`` answer (the
    daemon's in-memory ring incl. pinned errors).  Pure so tests can pin
    the layout."""
    out = []
    for e in entries:
        out.append(f"{e.get('stamp', 0.0):.6f} {e.get('level', 0):3d} "
                   f"{e.get('subsys', '?')}: {e.get('message', '')}")
    return out


def render_crash_info(info: Dict) -> List[str]:
    """Render `ceph crash info` (reference layout in miniature): the
    report header, the backtrace, then the captured dump_recent ring."""
    import time as _time

    lines = [
        f"crash_id: {info.get('crash_id', '')}",
        f"entity:   {info.get('entity', '')}",
        f"stamp:    "
        f"{_time.strftime('%Y-%m-%dT%H:%M:%S', _time.localtime(info.get('stamp', 0.0)))}",
        f"version:  {info.get('version', '')}",
        f"archived: {bool(info.get('archived'))}",
        f"exception: {info.get('exception', '')}",
        "backtrace:",
    ]
    for ln in str(info.get("backtrace", "")).splitlines():
        lines.append(f"    {ln}")
    recent = info.get("recent") or []
    lines.append(f"recent events ({len(recent)}):")
    for e in recent:
        lines.append(f"    {e.get('stamp', 0.0):.6f} "
                     f"{e.get('level', 0):3d} {e.get('subsys', '?')}: "
                     f"{e.get('message', '')}")
    return lines


def render_tier_status(status: Dict) -> List[str]:
    """Render an asok ``tier status`` answer: residency totals, page
    occupancy, dirty bytes, and per-pool cache_mode.  Pure so tests can
    pin the layout."""
    lines = [
        f"tier: {'enabled' if status.get('enabled') else 'disabled'}"
        f" residency={'on' if status.get('device_residency') else 'off'}",
        f"  resident: {status.get('resident_entries', 0)} entries / "
        f"{status.get('resident_bytes', 0)} B (memo "
        f"{status.get('memo_bytes', 0)} B) target "
        f"{status.get('target_max_bytes', 0)} B "
        f"full_ratio {status.get('cache_target_full_ratio', 0)} "
        f"dirty_ratio {status.get('cache_target_dirty_ratio', 0)}",
    ]
    modes = status.get("cache_mode") or {}
    if modes:
        lines.append("  cache_mode: " + "  ".join(
            f"{pool}={mode}" for pool, mode in sorted(modes.items())))
    ps = status.get("pagestore")
    if ps:
        lines.append(
            f"  pages: {ps.get('pages_used', 0)}/{ps.get('pages_total', 0)}"
            f" x {ps.get('page_bytes', 0)} B  dirty "
            f"{ps.get('dirty_pages', 0)}p/{ps.get('dirty_bytes', 0)}B "
            f"({ps.get('dirty_entries', 0)} entries)  partial "
            f"{ps.get('partial_residents', 0)}  frag_saved "
            f"{ps.get('frag_saved_bytes', 0)}B")
    else:
        lines.append("  pages: (monolithic resident store)")
    lines.append(f"  hit_set_archives: "
                 f"{status.get('hit_set_archives', 0)}")
    return lines


# admin-command renderers, shared by `ceph daemon ASOK CMD` and
# `ceph tell TARGET CMD` (same command surface, two transports)
ASOK_RENDERERS = {"dump_op_queue": render_op_queue,
                  "dump_reactors": render_reactors,
                  "log dump": render_log_dump,
                  "log dump_recent": render_log_dump,
                  "tier status": render_tier_status}


def print_asok_result(prefix: str, result, fmt: str) -> None:
    renderer = ASOK_RENDERERS.get(prefix)
    if fmt == "json" or renderer is None:
        print(json.dumps(result, indent=1, default=repr))
    else:
        for line in renderer(result):
            print(line)


def _pg_states(osdmap) -> List[Dict]:
    """Per-PG rows derived from the map: acting set, primary, state
    (active+clean, or degraded when acting has holes) — the map-derived
    half of the reference's `pg dump` (per-OSD runtime stats live behind
    each daemon's admin socket)."""
    from ceph_tpu.rados.crush import CRUSH_ITEM_NONE

    rows = []
    for pool in osdmap.pools.values():
        for pg in range(pool.pg_num):
            acting = osdmap.pg_to_acting(pool, pg)
            holes = sum(1 for a in acting if a == CRUSH_ITEM_NONE)
            live = [a for a in acting if a != CRUSH_ITEM_NONE]
            primary = osdmap.primary_of(
                acting, seed=(pool.pool_id << 20) | pg) if live else -1
            if holes == 0:
                state = "active+clean"
            elif len(live) >= pool.min_size:
                state = "active+degraded"
            else:
                state = "incomplete"
            rows.append({"pgid": f"{pool.pool_id}.{pg:x}", "state": state,
                         "acting": acting, "primary": primary})
    return rows


def render_health(health: Dict, detail: bool = False) -> List[str]:
    """Render the mon's aggregated health document (the server-side
    HealthMonitor answer — map-derived checks PLUS daemon-reported
    SLOW_OPS / BREAKER_OPEN / TIER_OVER_TARGET, mutes applied) in the
    reference `ceph health [detail]` layout.  Pure so tests can pin the
    rendering of every check type."""
    lines = [health.get("status", "HEALTH_OK")]
    for name, c in sorted((health.get("checks") or {}).items()):
        sev = c.get("severity", "warning").upper()
        lines.append(f"  [{'ERR' if sev == 'ERROR' else 'WRN'}] {name}: "
                     f"{c.get('summary', '')}")
        if detail:
            for d in c.get("detail") or []:
                lines.append(f"      {d}")
    muted = health.get("muted") or {}
    for name, c in sorted(muted.items()):
        extra = (f" (expires in {c['expires_in']:g}s)"
                 if c.get("expires_in") else "")
        lines.append(f"  (muted) {name}: {c.get('summary', '')}{extra}")
    return lines


def render_osd_df(rows: List[Dict], osdmap=None) -> List[str]:
    """Render `ceph osd df` from the mon's aggregated utilization view
    (client.osd_df rows): crush WEIGHT and the 0..1 REWEIGHT overlay
    (the `osd out/in/reweight` plane), size/use/avail, %USE, and the
    fullness STATE with nearfull/backfillfull/FULL highlighting.  Pure
    so tests can pin the layout."""
    lines = [f"{'ID':<4} {'STATUS':<7} {'WEIGHT':>7} {'REWEIGHT':>8} "
             f"{'SIZE':>12} {'USE':>12} {'AVAIL':>12} {'%USE':>7} "
             f"{'OBJECTS':>8}  STATE"]
    total_bytes = used_bytes = 0
    for r in rows:
        status = "up" if r.get("up", True) else "down"
        if r.get("error"):
            status = "error"
        if not r.get("in", True):
            status += "/out"
        total = int(r.get("total", 0) or 0)
        used = int(r.get("used", 0) or 0)
        if total:  # TOTAL %USE only over capacity-bearing OSDs
            total_bytes += total
            used_bytes += used
        pct = f"{100.0 * used / total:6.2f}%" if total else "      -"
        state = r.get("state", "") or "-"
        if state == "full":
            state = "FULL"  # the one that blocks writes stands out
        # WEIGHT = crush weight; REWEIGHT = the 0..1 overlay (rows from
        # a pre-r18 mon carry only the historic "weight" = overlay)
        reweight = float(r.get("reweight", r.get("weight", 1.0)))
        crush_w = float(r.get("crush_weight", 1.0))
        lines.append(
            f"{r.get('id', '?'):<4} {status:<7} "
            f"{crush_w:>7.4f} {reweight:>8.4f} {total:>12} "
            f"{used:>12} {int(r.get('avail', 0) or 0):>12} {pct:>7} "
            f"{int(r.get('num_objects', 0) or 0):>8}  {state}")
    if total_bytes:
        pct = f"{100.0 * used_bytes / total_bytes:6.2f}%"
        lines.append(f"TOTAL {'':<22} {total_bytes:>12} {used_bytes:>12} "
                     f"{max(0, total_bytes - used_bytes):>12} {pct:>7}")
    if osdmap is not None:
        nf, bf, fl = osdmap.fullness_ratios()
        lines.append(f"ratios: nearfull {nf:g}  backfillfull {bf:g}  "
                     f"full {fl:g}")
    return lines


def _osd_tree(osdmap) -> List[Dict]:
    """Flattened crush tree rows (reference `ceph osd tree` layout):
    buckets depth-first, devices with up/in status, crush WEIGHT and
    the 0..1 REWEIGHT overlay."""
    from ceph_tpu.rados.types import osd_crush_weight

    crush = osdmap.crush
    rows: List[Dict] = []
    seen = set()

    def device_row(osd_id: int, depth: int) -> Dict:
        info = osdmap.osds.get(osd_id)
        return {
            "id": osd_id, "name": f"osd.{osd_id}", "type": "osd",
            "depth": depth,
            # WEIGHT = crush weight (the OsdInfo record is authoritative
            # — bucket weights reset on crush rebuilds); REWEIGHT = the
            # admin overlay
            "weight": osd_crush_weight(info) if info else 1.0,
            "reweight": info.weight if info else 1.0,
            "status": "up" if info and info.up else "down",
            "in": bool(info and info.in_cluster),
        }

    def subtree_weight(bid: int) -> float:
        # a bucket's placement weight IS its subtree sum (stored parent
        # edge weights are informational) — same rule the straw2 draw
        # applies via _effective_weight
        total = 0.0
        for d in crush.subtree_devices(bid):
            info = osdmap.osds.get(d)
            total += osd_crush_weight(info) if info \
                else crush.device_weights.get(d, 1.0)
        return total

    def walk(bid: int, depth: int) -> None:
        b = crush.buckets.get(bid)
        if b is None or bid in seen:
            return
        seen.add(bid)
        rows.append({"id": b.id, "name": b.name, "type": b.type,
                     "depth": depth, "weight": subtree_weight(bid)})
        for item in b.items:
            if item < 0:
                walk(item, depth + 1)
            else:
                rows.append(device_row(item, depth + 1))
    walk(crush.root_id, 0)
    # stray devices not in any bucket (flat maps place all under root)
    for osd_id in sorted(osdmap.osds):
        if not any(r.get("name") == f"osd.{osd_id}" for r in rows):
            rows.append(device_row(osd_id, 1))
    return rows


def render_osd_tree(rows: List[Dict]) -> List[str]:
    """Render `ceph osd tree` rows (_osd_tree): bucket lines, then
    device lines with WEIGHT / REWEIGHT / status and the (out) marker.
    Pure so tests can pin the layout."""
    lines = [f"{'ID':>4} {'WEIGHT':>8} {'REWEIGHT':>8}  NAME/STATUS"]
    for r in rows:
        pad = "  " * r.get("depth", 0)
        if r["type"] == "osd":
            lines.append(
                f"{r['id']:>4} {r.get('weight', 1.0):>8.4f} "
                f"{r.get('reweight', 1.0):>8.4f}  {pad}{r['name']:<12}"
                f"{r['status']}"
                f"{'' if r.get('in', True) else ' (out)'}")
        else:
            lines.append(f"{r['id']:>4} {r.get('weight', 0.0):>8.4f} "
                         f"{'':>8}  {pad}{r['type']} {r['name']}")
    return lines


def render_predicate_reply(reply) -> List[str]:
    """Render an MOsdPredicateReply (`osd safe-to-destroy` /
    `osd ok-to-stop`).  Pure so tests can pin the layout."""
    lines = [f"{reply.op}: {'SAFE' if reply.safe else 'NOT SAFE'} "
             f"({reply.pgs_checked} pgs checked)"]
    if reply.unsafe_ids:
        lines.append("  unsafe: "
                     + ", ".join(f"osd.{i}" for i in reply.unsafe_ids))
    for r in reply.reasons:
        lines.append(f"  - {r}")
    if getattr(reply, "dirty_blocked", 0):
        lines.append(f"  unflushed dirty objects at risk: "
                     f"{reply.dirty_blocked}")
        for k in getattr(reply, "dirty_keys", ()) or ():
            lines.append(f"    * {k}")
    return lines


async def _df(client) -> List[Dict]:
    from ceph_tpu.rados.types import ALL_NSPACES

    rows = []
    for pool in client.osdmap.pools.values():
        # df is a pool-wide stat: include every namespace
        objects = await client.list_objects(pool.pool_id,
                                            nspace=ALL_NSPACES)
        rows.append({"pool": pool.name, "id": pool.pool_id,
                     "type": pool.pool_type, "objects": len(objects)})
    return rows


async def run(args) -> int:
    from ceph_tpu.rados.client import RadosClient

    if args.words and args.words[0] == "daemon":
        # `ceph daemon ASOK CMD [k=v...]` role: one admin-socket command
        # against a running daemon — no mon needed
        if len(args.words) < 3:
            print("usage: daemon ASOK_PATH COMMAND [k=v...]",
                  file=sys.stderr)
            return 2
        from ceph_tpu.common.admin_socket import asok_command

        path, prefix = args.words[1], " ".join(args.words[2:3])
        # multi-word asok prefixes ("perf dump", "tier status") and
        # k=v arguments after them
        rest = args.words[3:]
        while rest and "=" not in rest[0]:
            prefix += " " + rest.pop(0)
        kwargs = dict(kv.split("=", 1) for kv in rest)
        result = await asok_command(path, prefix, **kwargs)
        print_asok_result(prefix, result, args.format)
        return 0
    if not args.mon:
        print("--mon is required for cluster commands", file=sys.stderr)
        return 2
    host, port = args.mon.rsplit(":", 1)
    client = RadosClient((host, int(port)))
    await client.start()
    try:
        await client.refresh_map()
        m = client.osdmap
        cmd = " ".join(args.words)
        if args.watch:
            # `ceph -w`: print the retained tail, then follow the stream
            from ceph_tpu.rados.clog import PRIO_BY_NAME

            level = PRIO_BY_NAME.get(args.watch_level.lower(), 0) \
                if args.watch_level else 0

            def _print(entry):
                print(entry.render(), flush=True)

            tail = await client.watch_cluster_log(
                _print, level=level, channel=args.watch_channel)
            for e in tail:
                print(e.render())
            try:
                if args.run_for > 0:
                    await asyncio.sleep(args.run_for)
                else:
                    while True:
                        await asyncio.sleep(3600)
            except (KeyboardInterrupt, asyncio.CancelledError):
                pass
            return 0
        if args.words[:2] == ["log", "last"]:
            from ceph_tpu.rados.clog import PRIO_BY_NAME

            rest = args.words[2:]
            n = int(rest.pop(0)) if rest and rest[0].isdigit() else 0
            level = 0
            if rest and rest[0].lower() in PRIO_BY_NAME:
                level = PRIO_BY_NAME[rest.pop(0).lower()]
            channel = rest.pop(0) if rest else ""
            entries = await client.log_last(n=n, level=level,
                                            channel=channel)
            if args.format == "json":
                print(json.dumps([vars(e) for e in entries]))
            else:
                for e in entries:
                    print(e.render())
            return 0
        if args.words and args.words[0] == "crash":
            sub = args.words[1] if len(args.words) > 1 else "ls"
            if sub == "ls":
                rows = await client.crash_ls()
                if args.format == "json":
                    print(json.dumps(rows))
                else:
                    import time as _time

                    for r in rows:
                        ts = _time.strftime(
                            "%Y-%m-%dT%H:%M:%S",
                            _time.localtime(r.get("stamp", 0.0)))
                        print(f"{r['crash_id']:<44} {r['entity']:<10} "
                              f"{ts}"
                              + ("  (archived)" if r.get("archived")
                                 else ""))
                return 0
            if sub == "info" and len(args.words) == 3:
                info = await client.crash_info(args.words[2])
                if args.format == "json":
                    print(json.dumps(info, default=repr))
                else:
                    for line in render_crash_info(info):
                        print(line)
                return 0
            if sub == "archive" and len(args.words) == 3:
                await client.crash_archive(args.words[2])
                print(f"archived {args.words[2]}")
                return 0
            if sub == "archive-all":
                rows = await client.crash_archive()
                print(f"archived {len(rows)} crash reports")
                return 0
            if sub == "prune" and len(args.words) == 3:
                rows = await client.crash_prune(
                    float(args.words[2]) * 24 * 3600.0)
                print(f"{len(rows)} crash reports remain")
                return 0
            print("usage: crash ls | info ID | archive ID | archive-all"
                  " | prune KEEP_DAYS", file=sys.stderr)
            return 2
        if args.words and args.words[0] == "tell":
            # `ceph tell TARGET CMD [k=v...]`: remote asok command —
            # `tell osd.0 config set key=debug_ms value=10` is the
            # runtime-verbosity workflow
            if len(args.words) < 3:
                print("usage: tell TARGET COMMAND [k=v...]",
                      file=sys.stderr)
                return 2
            target, prefix = args.words[1], args.words[2]
            rest = args.words[3:]
            while rest and "=" not in rest[0]:
                prefix += " " + rest.pop(0)
            kwargs = dict(kv.split("=", 1) for kv in rest)
            result = await client.tell(target, prefix, **kwargs)
            print_asok_result(prefix, result, args.format)
            return 0
        pg_rows = _pg_states(m)
        if cmd == "status":
            # health comes from the MON's aggregation (HealthMonitor
            # role) — the authority that also sees daemon-reported
            # checks, not client-side osdmap math
            health = await client.get_health()
            up = sum(1 for o in m.osds.values() if o.up)
            inc = sum(1 for o in m.osds.values() if o.in_cluster)
            clean = sum(1 for r in pg_rows if r["state"] == "active+clean")
            out = {
                "health": health["status"],
                "checks": sorted(health.get("checks") or {}),
                "osdmap": {"epoch": m.epoch, "num_osds": len(m.osds),
                           "num_up_osds": up, "num_in_osds": inc},
                "pgmap": {"num_pgs": len(pg_rows),
                          "active_clean": clean},
                "pools": len(m.pools),
            }
            if args.format == "json":
                print(json.dumps(out))
            else:
                print(f"  health: {out['health']}")
                for line in render_health(health)[1:]:
                    print(f"  {line.strip()}")
                print(f"  osdmap: e{m.epoch}: {len(m.osds)} osds: "
                      f"{up} up, {inc} in")
                print(f"  pgmap: {len(pg_rows)} pgs, {clean} active+clean"
                      f", {len(m.pools)} pools")
            return 0
        if cmd in ("health", "health detail"):
            detail = cmd == "health detail"
            health = await client.get_health(detail=detail)
            if args.format == "json":
                print(json.dumps(health))
            else:
                for line in render_health(health, detail=detail):
                    print(line)
            return 0
        if args.words[:2] == ["health", "mute"] and len(args.words) >= 3:
            try:
                ttl = float(args.words[3]) if len(args.words) > 3 else 0.0
            except ValueError:
                print("usage: health mute CHECK [TTL_SECONDS]",
                      file=sys.stderr)
                return 2
            health = await client.health_mute(args.words[2], ttl=ttl)
            print(f"muted {args.words[2]}"
                  + (f" for {ttl:g}s" if ttl else ""))
            for line in render_health(health):
                print(line)
            return 0
        if args.words[:2] == ["health", "unmute"] and len(args.words) == 3:
            health = await client.health_mute(args.words[2], unmute=True)
            print(f"unmuted {args.words[2]}")
            for line in render_health(health):
                print(line)
            return 0
        if cmd == "osd tree":
            rows = _osd_tree(m)
            if args.format == "json":
                print(json.dumps(rows))
            else:
                for line in render_osd_tree(rows):
                    print(line)
            return 0
        if cmd == "pg dump":
            if args.format == "json":
                print(json.dumps(pg_rows))
            else:
                for r in pg_rows:
                    print(f"{r['pgid']:<10} {r['state']:<18} "
                          f"acting {r['acting']} primary {r['primary']}")
            return 0
        if cmd == "df":
            rows = await _df(client)
            if args.format == "json":
                print(json.dumps(rows))
            else:
                for r in rows:
                    print(f"{r['pool']:<20} id {r['id']:<4} "
                          f"{r['type']:<12} {r['objects']} objects")
            return 0
        if args.words[:3] == ["osd", "pool", "ls"]:
            rows = [{"id": p.pool_id, "name": p.name,
                     "type": p.pool_type, "pg_num": p.pg_num,
                     "size": p.size}
                    for p in sorted(m.pools.values(),
                                    key=lambda x: x.pool_id)]
            if args.format == "json":
                print(json.dumps(rows))
            else:
                for r in rows:
                    print(f"{r['id']:>3} {r['name']:<20} {r['type']:<11} "
                          f"pg_num {r['pg_num']} size {r['size']}")
            return 0
        if args.words[:3] == ["osd", "pool", "create"]:
            rest = args.words[3:]
            if not rest:
                print("usage: osd pool create NAME [replicated|k=v ...]",
                      file=sys.stderr)
                return 2
            name, params = rest[0], rest[1:]
            if params and params[0] == "replicated":
                extra = params[1:]
                pg_num = 8
                if extra and extra[0].isdigit():
                    pg_num = int(extra.pop(0))
                if extra:
                    print(f"unrecognized arguments: {extra}",
                          file=sys.stderr)
                    return 2
                pool_id = await client.create_pool(
                    name, pool_type="replicated", pg_num=pg_num)
            else:
                bad = [kv for kv in params if "=" not in kv]
                if bad:
                    # silently dropping tokens here could turn a typo'd
                    # `replicated` request into an EC pool
                    print(f"unrecognized arguments: {bad}",
                          file=sys.stderr)
                    return 2
                profile = dict(kv.split("=", 1) for kv in params)
                pool_id = await client.create_pool(
                    name, profile=profile or None)
            print(f"pool '{name}' created (id {pool_id})")
            return 0
        if args.words[:3] == ["osd", "pool", "set"]:
            rest = args.words[3:]
            if len(rest) != 3:
                print("usage: osd pool set NAME KEY VALUE",
                      file=sys.stderr)
                return 2
            name, key, value = rest
            pool = m.pool_by_name(name)
            if pool is None:
                print(f"no pool {name!r}", file=sys.stderr)
                return 2
            await client.pool_set(pool.pool_id, key, value)
            print(f"set pool {name} {key} = {value}")
            return 0
        if cmd == "osd df":
            # per-OSD utilization + fullness (reference `ceph osd df`):
            # ONE aggregated query against the mon (the view its
            # fullness derivation runs on) instead of N direct per-OSD
            # statfs ops; client.osd_df falls back to direct polling
            # when the mon is old
            util = await client.osd_df()
            rows = [{"id": osd_id, **r}
                    for osd_id, r in sorted(util.items())]
            if args.format == "json":
                print(json.dumps(rows))
            else:
                for line in render_osd_df(rows, m):
                    print(line)
            return 0
        if len(args.words) == 3 and args.words[0] == "osd" \
                and args.words[1] in ("set-nearfull-ratio",
                                      "set-backfillfull-ratio",
                                      "set-full-ratio"):
            which = args.words[1][len("set-"):-len("-ratio")]
            try:
                ratio = float(args.words[2])
            except ValueError:
                print(f"bad ratio {args.words[2]!r}", file=sys.stderr)
                return 2
            try:
                await client.osd_set_full_ratio(which, ratio)
            except Exception as e:
                print(f"Error: {e}", file=sys.stderr)
                return 1
            print(f"osd set-{which}-ratio {ratio:g}")
            return 0
        if args.words[:2] in (["osd", "out"], ["osd", "in"]) \
                and len(args.words) >= 3:
            # `ceph osd out/in ID [ID...]` — elastic membership
            verb = args.words[1]
            ids = []
            for raw in args.words[2:]:
                # validate the WHOLE list before mutating anything: a
                # typo mid-list must not leave the first ids draining
                try:
                    osd_id = int(raw.split(".")[-1])
                except ValueError:
                    print(f"bad osd id {raw!r}", file=sys.stderr)
                    return 2
                if osd_id not in m.osds:
                    print(f"no osd.{osd_id}", file=sys.stderr)
                    return 2
                ids.append(osd_id)
            for osd_id in ids:
                if verb == "out":
                    await client.osd_out(osd_id)
                else:
                    await client.osd_in(osd_id)
                print(f"marked {verb} osd.{osd_id}")
            return 0
        if args.words[:2] == ["osd", "reweight"] and len(args.words) == 4:
            try:
                osd_id = int(args.words[2].split(".")[-1])
                weight = float(args.words[3])
            except ValueError:
                print("usage: osd reweight ID WEIGHT(0..1)",
                      file=sys.stderr)
                return 2
            if osd_id not in m.osds or not (0.0 <= weight <= 1.0):
                print(f"need an existing osd id and weight in [0,1]",
                      file=sys.stderr)
                return 2
            await client.osd_reweight(osd_id, weight)
            print(f"reweighted osd.{osd_id} to {weight:g}")
            return 0
        if args.words[:3] == ["osd", "crush", "reweight"] \
                and len(args.words) == 5:
            try:
                osd_id = int(args.words[3].split(".")[-1])
                weight = float(args.words[4])
            except ValueError:
                print("usage: osd crush reweight osd.ID WEIGHT",
                      file=sys.stderr)
                return 2
            if osd_id not in m.osds or weight < 0:
                print("need an existing osd id and weight >= 0",
                      file=sys.stderr)
                return 2
            await client.osd_crush_reweight(osd_id, weight)
            print(f"crush reweighted osd.{osd_id} to {weight:g}")
            return 0
        if args.words[:2] == ["osd", "crush"] and len(args.words) >= 3 \
                and args.words[2] in ("add-bucket", "add", "set",
                                      "move", "rm"):
            # `ceph osd crush add-bucket NAME TYPE [ROOT]`
            # `ceph osd crush add|set osd.N WEIGHT [BUCKET]`
            # `ceph osd crush move NAME BUCKET`
            # `ceph osd crush rm NAME [--force via confirm flag]`
            op, rest = args.words[2], args.words[3:]
            kw = {}
            try:
                if op == "add-bucket":
                    if len(rest) not in (2, 3):
                        raise ValueError(
                            "usage: osd crush add-bucket NAME TYPE [ROOT]")
                    kw = dict(name=rest[0], bucket_type=rest[1],
                              dest=rest[2] if len(rest) == 3 else "")
                elif op in ("add", "set"):
                    if len(rest) not in (2, 3):
                        raise ValueError(
                            f"usage: osd crush {op} osd.N WEIGHT [BUCKET]")
                    kw = dict(name=rest[0], weight=float(rest[1]),
                              dest=rest[2] if len(rest) == 3 else "")
                elif op == "move":
                    if len(rest) != 2:
                        raise ValueError(
                            "usage: osd crush move NAME BUCKET")
                    kw = dict(name=rest[0], dest=rest[1])
                else:  # rm
                    if len(rest) != 1:
                        raise ValueError("usage: osd crush rm NAME")
                    kw = dict(name=rest[0],
                              force=bool(args.confirm_destroy))
            except ValueError as e:
                print(str(e), file=sys.stderr)
                return 2
            try:
                epoch = await client.osd_crush_op(op, **kw)
            except Exception as e:
                print(f"Error: {e}", file=sys.stderr)
                return 1
            print(f"crush {op} {kw['name']} done (epoch {epoch})")
            return 0
        if args.words[:2] in (["osd", "safe-to-destroy"],
                              ["osd", "ok-to-stop"]) \
                and len(args.words) >= 3:
            try:
                ids = [int(w.split(".")[-1]) for w in args.words[2:]]
            except ValueError:
                print(f"usage: osd {args.words[1]} ID [ID...]",
                      file=sys.stderr)
                return 2
            reply = await client.osd_predicate(args.words[1], ids)
            if args.format == "json":
                print(json.dumps({
                    "op": reply.op, "safe": reply.safe,
                    "unsafe_ids": reply.unsafe_ids,
                    "reasons": reply.reasons,
                    "pgs_checked": reply.pgs_checked,
                    "dirty_blocked": reply.dirty_blocked,
                    "dirty_keys": reply.dirty_keys}))
            else:
                for line in render_predicate_reply(reply):
                    print(line)
            return 0 if reply.safe else 1
        if args.words[:2] == ["osd", "purge"] and len(args.words) == 3:
            try:
                osd_id = int(args.words[2].split(".")[-1])
            except ValueError:
                print("usage: osd purge ID [--yes-i-really-really-"
                      "mean-it to force]", file=sys.stderr)
                return 2
            try:
                await client.osd_purge(osd_id,
                                       force=bool(args.confirm_destroy))
            except Exception as e:
                print(f"Error: {e}", file=sys.stderr)
                return 1
            print(f"purged osd.{osd_id}")
            return 0
        if args.words[:2] in (["pg", "scrub"], ["pg", "repair"]) \
                and len(args.words) == 3:
            # `ceph pg scrub/repair PGID` — MCommand tell at the primary
            try:
                if args.words[1] == "scrub":
                    result = await client.pg_scrub(args.words[2])
                else:
                    result = await client.pg_repair(args.words[2])
            except Exception as e:
                print(f"Error: {e}", file=sys.stderr)
                return 1
            if args.format == "json":
                print(json.dumps(result, default=repr))
            else:
                extra = ""
                if "verified_clean" in result:
                    extra = (", verified clean"
                             if result["verified_clean"]
                             else f", {result.get('errors_after_repair')}"
                                  f" errors REMAIN after repair")
                print(f"pg {result.get('pgid', args.words[2])} "
                      f"{args.words[1]}: "
                      f"{result.get('scrubbed', 0)} objects, "
                      f"{result.get('errors', 0)} errors, "
                      f"{result.get('repaired', 0)} repaired{extra}")
            return 0
        if args.words[:3] in (["osd", "pool", "mksnap"],
                              ["osd", "pool", "rmsnap"]):
            rest = args.words[3:]
            if len(rest) != 2:
                print(f"usage: osd pool {args.words[2]} POOL SNAP",
                      file=sys.stderr)
                return 2
            pool = m.pool_by_name(rest[0])
            if pool is None:
                print(f"no pool {rest[0]!r}", file=sys.stderr)
                return 2
            if args.words[2] == "mksnap":
                await client.pool_snap_create(pool.pool_id, rest[1])
                print(f"created pool {rest[0]} snap {rest[1]}")
            else:
                await client.pool_snap_remove(pool.pool_id, rest[1])
                print(f"removed pool {rest[0]} snap {rest[1]}")
            return 0
        if args.words[:3] == ["osd", "pool", "rm"]:
            rest = args.words[3:]
            confirmed = args.confirm_destroy
            if len(rest) != 2 or rest[0] != rest[1] or not confirmed:
                # reference guard: the name twice AND the flag
                print("Error EPERM: pool removal requires the pool name "
                      "TWICE plus --yes-i-really-really-mean-it",
                      file=sys.stderr)
                return 1
            pool = m.pool_by_name(rest[0])
            if pool is None:
                print(f"no pool {rest[0]!r}", file=sys.stderr)
                return 2
            await client.delete_pool(pool.pool_id, rest[0])
            print(f"pool '{rest[0]}' removed")
            return 0
        print(f"unknown command: {cmd}", file=sys.stderr)
        return 2
    finally:
        await client.stop()


def main(argv=None) -> int:
    return asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
