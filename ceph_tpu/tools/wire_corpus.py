"""Wire-format corpus: archived encoded frames of the core message
types, replayed against today's decoder (reference ceph-object-corpus +
src/test/encoding/readable.sh: every archived past version must stay
decodable, so an accidental field rename / layout change is caught the
round it happens, not at the first mixed-version cluster).

    python -m ceph_tpu.tools.wire_corpus --create          # archive current
    python -m ceph_tpu.tools.wire_corpus --check           # replay archive
    python -m ceph_tpu.tools.wire_corpus --check --strict  # + coverage walk

``--strict`` additionally fails on any FIXED message type missing
corpus coverage (no archived frame), dencoder coverage (its fixed codec
must round-trip a default instance), or — for versioned (v2+) types — a
golden old-build frame under corpus/wire/golden.  The walk lives in
``coverage_gaps()`` so the tpu-lint wire-ABI family reuses the SAME
implementation (one source of truth for what "covered" means).

Each archived frame is a self-contained binary file:

    [u16 type_id][u16 version][u8 fixed][u32 plen][payload][u32 blen][blob]

plus a sidecar .json with the expected decoded field values (bytes as
hex) — the check decodes the frame with TODAY's decode_message and
compares field-for-field, so both the binary layout and the field NAMES
are pinned.  Data-plane types archive their FIXED layout; control-plane
types archive their pickled layout.
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys
from typing import Any, Dict, List, Tuple

_FRAME_HDR = struct.Struct("<HHBI")

CORPUS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "corpus", "wire")


def _sample_messages() -> List[Any]:
    """Representative instances of the core message set — every field
    non-default so a dropped/renamed field cannot hide behind a
    default value."""
    from ceph_tpu.rados import types as t
    from ceph_tpu.rados.tiering import HitSetArchive

    # deterministic hit-set archive (explicit clocks, seeded blake2b
    # hashing): the MOSDPGHitSet frame below pins the BloomHitSet /
    # HitSetArchive BINARY encoding alongside the message layout —
    # an accidental re-layout of either fails the corpus check
    arch = HitSetArchive(period=2.0, count=4, target_size=32,
                         fpp=0.05, seed=77, now=100.0)
    arch.record("corpus/hot", now=100.5)
    arch.record("corpus/hot", now=102.5)  # rotates the first interval
    arch.record("corpus/warm", now=102.6)

    from ceph_tpu.rados.messenger import MLaneHello, MLaneSegment
    from ceph_tpu.rados.clog import ClogEntry, encode_entries

    # deterministic cluster-log blob: the MLog/MLogReply/MCrashReport
    # frames below pin the ClogEntry BINARY codec (append-only records)
    # alongside the message layouts
    clog_blob = encode_entries([
        ClogEntry(stamp=1700000000.25, name="osd.3", channel="cluster",
                  prio=3, seq=9001, message="corpus warn line", idx=41),
        ClogEntry(stamp=1700000001.5, name="mon.0", channel="audit",
                  prio=1, seq=77, message="from='client' cmd='MPoolSet'",
                  idx=42),
    ])

    return [
        t.MOSDOp(op="write", pool_id=3, oid="corpus/oid", data=b"payload",
                 epoch=11, reqid="req-1", offset=4096, cls="lock",
                 method="lock", snapc_seq=9, snapc_snaps=[9, 4, 2],
                 snap_read=7, snap_id=5, pg=12, cursor="after",
                 max_entries=64, nspace="blue", fadvise="willneed",
                 trace_id="deadbeefcafef00d", span_id="0123456789abcdef",
                 client="client.gold.7", gseq=17),
        t.MOSDOp(op="multi", pool_id=1, oid="m", reqid="r2",
                 ops=[("setxattr", {"name": "a", "value": b"v"}),
                      ("omap_set", {"entries": {"k": b"x"}})]),
        t.MOSDOpReply(ok=False, error="nope", code=-17, data=b"reply",
                      oids=["a", "b"], cursor="cur", backoff=0.25,
                      reqid="rq", version=(7 << 32) | 3, map_epoch=21,
                      gseq=18),
        t.MECSubWrite(pool_id=2, pg=5, from_osd=3, epoch=13, oid="obj",
                      shard=4, chunk=b"chunkdata", version=99,
                      object_size=1234, chunk_crc=0xDEAD, tid="t1",
                      reply_to=("127.0.0.1", 6800), log_entry=b"LE",
                      chunk_off=8192, shard_size=65536, prior_version=42,
                      hinfo=b"HINFO", trace_id="deadbeefcafef00d",
                      span_id="fedcba9876543210", gseq=19),
        t.MECSubWriteReply(tid="t1", shard=4, ok=False,
                           trace_id="deadbeefcafef00d",
                           span_id="fedcba9876543210", gseq=20),
        t.MECSubRead(pool_id=2, pg=5, oid="obj", shard=1, tid="t2",
                     reply_to=("host", 1), extents=[(0, 4096), (8192, 64)],
                     want_hinfo=True, gseq=21),
        # chunk_crc stays default: it is SENDER-LOCAL (not in
        # FIXED_FIELDS — the frame's blob-crc slot carries it), so the
        # decoded archive must see the dataclass default
        t.MECSubReadReply(tid="t2", shard=1, ok=True, chunk=b"bytes",
                          version=7, object_size=55, hinfo=b"H", gseq=22),
        t.MECSubDelete(pool_id=1, pg=2, oid="gone", shard=0, tid="t3",
                       reply_to=("h", 2)),
        # writeback fast-ack plane: the raw-dirty install (every field
        # non-default) plus the post-flush clear broadcast — both legs
        # of the cache-tier durability quorum are corpus-pinned
        t.MCacheDirty(pool_id=3, pg=6, from_osd=1, epoch=27,
                      oid="wb/obj", op="install", data=b"rawdirty",
                      version=41, object_size=8, tid="t-wb1",
                      reply_to=("127.0.0.1", 6802), log_entry=b"LE",
                      peers=[1, 2, 3], gseq=25),
        t.MCacheDirty(pool_id=3, pg=6, from_osd=1, epoch=28,
                      oid="wb/obj", op="clear", version=41,
                      object_size=8, gseq=26),
        t.MCacheDirtyAck(tid="t-wb1", osd=2, ok=False, gseq=27),
        t.MPushShard(pool_id=1, pg=0, oid="pushed", shard=2,
                     chunk=b"recovered", version=3, object_size=9,
                     hinfo=b"HH", gseq=23),
        t.MPushShard(pool_id=1, pg=0, oid="pushed2", shard=2,
                     chunk=b"r2", version=3, object_size=2,
                     xattrs={"lock.x": b"owner"}),
        t.MListShards(pool_id=4, tid="t4"),
        t.MFetchShards(pool_id=4, oid="a", tid="t5",
                       reply_to=("h", 9)),
        t.MPGInfoReq(pool_id=1, pg=7, tid="t6"),
        t.MPGLogReq(pool_id=1, pg=7, since=(3, 9), tid="t7"),
        t.MOSDPing(op="ping", from_osd=2, epoch=5),
        t.MGetMap(min_epoch=4, tid="t8"),
        t.MSnapOp(pool_id=2, op="mksnap", snap_id=0, name="snapname",
                  tid="t9"),
        t.MSnapOpReply(tid="t9", ok=False, error="bad", code=-22,
                       snap_id=6),
        t.MSetXattrs(pool_id=1, oid="x", shard=0,
                     xattrs={"k": b"v"}, removals=["old"]),
        t.MSetOmap(pool_id=1, oid="x", shard=0, clear=True,
                   entries={"a": b"1"}, removals=["b"]),
        t.MWatchNotify(pool_id=1, oid="w", notify_id="n1",
                       payload=b"ping"),
        t.MNotifyAck(notify_id="n1", watcher=("h", 3)),
        t.MBackfillReserve(pool_id=1, pg=3, op="request", from_osd=2,
                           tid="t10", reply_to=("h", 4)),
        # v2 carries the refusal reason ("toofull" = backfillfull
        # target); v1 frames decode with reason defaulting (golden)
        t.MBackfillReserveReply(tid="t10", osd_id=4, ok=False,
                                reason="toofull"),
        # liveness ping v4: health checks + the statfs the mon's
        # fullness derivation runs on (v3 golden pins truncated decode;
        # the scrub-era health SHAPE — OSD_SCRUB_ERRORS/PG_INCONSISTENT
        # riding the dict — is pinned here, with the pre-scrub-era
        # content replay-guarded by golden MPing.v4_prescrubera)
        t.MPing(osd_id=3, epoch=21, addr=("127.0.0.1", 6801),
                health={"SLOW_OPS": {"severity": "warning",
                                     "summary": "1 slow ops",
                                     "count": 1},
                        "OSD_SCRUB_ERRORS": {"severity": "error",
                                             "summary": "2 scrub errors",
                                             "count": 2},
                        "PG_INCONSISTENT": {
                            "severity": "error",
                            "summary": "1 pg(s) inconsistent",
                            "count": 1, "pgs": ["1.3"]}},
                statfs={"total": 1 << 30, "used": 900 << 20,
                        "avail": (1 << 30) - (900 << 20),
                        "num_objects": 12},
                # v5: the unflushed-dirt roster the mon's
                # safe-to-destroy / ok-to-stop predicates consume
                cache_dirty=[("3:wb/obj", [1, 2, 3]),
                             ("1:solo", [3])]),
        # v3: the embedded OsdInfo/incremental records grew the
        # crush_weight tail (golden MMapReply.v2_precrushweight pins
        # the pre-change decode).  Archived with default payloads —
        # the map itself is not JSON-able; the sidecar pins the field
        # NAMES and the golden frame pins a real-map decode.
        t.MMapReply(tid="t19"),
        t.MOsdMembership(op="crush-reweight", osd_id=4, weight=2.5,
                         tid="t20"),
        # runtime crush topology plane: the hierarchy-surgery command
        # (v2 tail: force) and its typed reply
        t.MCrushOp(op="move", name="host2", bucket_type="host",
                   dest="rack1", weight=3.5, tid="t21", force=True),
        t.MCrushOpReply(tid="t21", ok=False,
                        error="EINVAL: would create a cycle", epoch=55),
        # data-safety predicates: the query and the render-friendly
        # reply (v2 tail: the cache-dirt clause counters/keys)
        t.MOsdPredicate(op="ok-to-stop", osd_ids=[2, 5], tid="t22"),
        t.MOsdPredicateReply(tid="t22", op="ok-to-stop", safe=False,
                             unsafe_ids=[5],
                             reasons=["pg 1.3 would drop below "
                                      "min_size"],
                             pgs_checked=16, dirty_blocked=2,
                             dirty_keys=["3:wb/obj@osd.5"]),
        t.MSetFullRatio(which="backfillfull", ratio=0.9, tid="t18"),
        t.MOSDFailure(target_osd=4, from_osd=1, failed_for=12.5,
                      tid="t11"),
        t.MOSDBackoff(op="unblock", pool_id=2, pg=9, id="bk-1", epoch=33,
                      duration=1.5, trace_id="deadbeefcafef00d",
                      span_id="0011223344556677"),
        t.MOSDPGHitSet(pool_id=3, pg=7, from_osd=2, epoch=44,
                       archive=arch.encode(now=103.0),
                       trace_id="deadbeefcafef00d",
                       span_id="8899aabbccddeeff"),
        t.MGetHealth(tid="t12", detail=True),
        t.MHealthReply(tid="t12", health={
            "status": "HEALTH_WARN",
            "checks": {"SLOW_OPS": {"severity": "warning",
                                    "summary": "1 slow ops"}},
            "muted": {}}),
        t.MHealthMute(check="SLOW_OPS", ttl=30.0, unmute=False,
                      tid="t13"),
        # cluster log + crash telemetry plane (clog.py): the ClogEntry
        # blob codec and every frame of the plane are corpus-pinned
        t.MLog(who="osd.3", entries=clog_blob),
        t.MLogAck(who="osd.3", last_seq=9001),
        t.MLogSubscribe(tid="t14", channel="audit", level=3, last_n=20,
                        sub=True),
        t.MLogReply(tid="t14", entries=clog_blob),
        t.MCrashReport(entity="osd.3", crash_id="2026-08-03_12:00:00Z_abc",
                       stamp=1700000002.75, version="1.0.0-tpu",
                       exception="RuntimeError('corpus')",
                       backtrace="Traceback...\n  corpus frame\n",
                       recent=clog_blob, tid="t15"),
        t.MCrashReportAck(tid="t15", ok=False),
        t.MCrashQuery(tid="t16", op="prune", crash_id="2026-08-03_x",
                      keep=86400.0),
        t.MCrashQueryReply(tid="t16", ok=False, error="no crash",
                           crashes=[{"crash_id": "c1", "entity": "osd.1"}]),
        t.MCommand(tid="t17", target="osd.0", prefix="config set",
                   args={"key": "debug_ms", "value": "10"}),
        t.MCommandReply(tid="t17", ok=True, result={"success": True}),
        # wire-plane negotiation + fragmentation types (messenger.py):
        # the lane-handshake fields and the striped-segment layout are
        # corpus-pinned like every other data-plane type
        MLaneHello(group="aabbccdd00112233", lane=2, n_lanes=4,
                   proc="feedface", flags=1),
        MLaneSegment(gseq=24, idx=1, nfrags=3, total=48, off=16,
                     type_id=30, version=6, fixed=True,
                     header=b"HDRBYTES", chunk=b"C" * 16),
    ]


def _encode_frame(msg: Any) -> Tuple[bytes, Dict]:
    from ceph_tpu.rados.messenger import encode_payload_parts

    payload, blob, fixed = encode_payload_parts(msg)
    blob_b = b"" if blob is None else bytes(blob)
    frame = (_FRAME_HDR.pack(type(msg).TYPE_ID, type(msg).VERSION,
                             1 if fixed else 0, len(payload))
             + payload + struct.pack("<I", len(blob_b)) + blob_b)
    expect = {k: _norm(v) for k, v in msg.__dict__.items()}
    return frame, {"type": type(msg).__name__,
                   "type_id": type(msg).TYPE_ID,
                   "version": type(msg).VERSION,
                   "fixed": bool(fixed),
                   "fields": expect}


def _norm(v: Any) -> Any:
    """Decoded value -> comparable JSON-ish form (tuples and lists
    collapse; bytes to hex)."""
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"__hex__": bytes(v).hex()}
    if isinstance(v, tuple):
        return [_norm(x) for x in v]
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, dict):
        return {"__dict__": {k: _norm(x) for k, x in v.items()}}
    return v


def create(directory: str = CORPUS_DIR) -> int:
    os.makedirs(directory, exist_ok=True)
    names = set()
    for msg in _sample_messages():
        frame, meta = _encode_frame(msg)
        base = meta["type"]
        n = 2
        while base in names:  # numbered variants: nothing overwrites
            base = f"{meta['type']}.alt{n}"
            n += 1
        names.add(base)
        with open(os.path.join(directory, base + ".frame"), "wb") as f:
            f.write(frame)
        with open(os.path.join(directory, base + ".json"), "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
    print(f"archived {len(names)} frames to {directory}")
    return 0


def check(directory: str = CORPUS_DIR) -> int:
    import ceph_tpu.rados.types  # noqa: F401 — registers the message set
    from ceph_tpu.rados.messenger import decode_message

    failures = []
    frames = sorted(n for n in os.listdir(directory)
                    if n.endswith(".frame"))
    if not frames:
        print(f"no archived frames in {directory}", file=sys.stderr)
        return 1
    import dataclasses

    for name in frames:
        try:
            with open(os.path.join(directory, name), "rb") as f:
                raw = f.read()
            with open(os.path.join(directory,
                                   name[:-6] + ".json")) as f:
                meta = json.load(f)
            type_id, version, fixed, plen = _FRAME_HDR.unpack_from(raw, 0)
            off = _FRAME_HDR.size
            payload = raw[off:off + plen]
            off += plen
            (blen,) = struct.unpack_from("<I", raw, off)
            blob = raw[off + 4:off + 4 + blen] if blen else None
        except Exception as e:
            failures.append(f"{name}: unreadable archive entry: {e}")
            continue
        try:
            msg = decode_message(type_id, version, payload, blob,
                                 bool(fixed))
        except Exception as e:
            failures.append(f"{name}: decode failed: {e}")
            continue
        got = {k: _norm(v) for k, v in msg.__dict__.items()}
        want = meta["fields"]
        if got != want:
            diffs = sorted(set(got) ^ set(want)) or [
                k for k in want if got.get(k) != want[k]]
            failures.append(f"{name}: field drift: {diffs}")
            continue
        # pickled payloads restore ARCHIVED attribute names verbatim, so
        # equality above cannot catch a rename of a control-plane field:
        # also pin the archive's names against the CURRENT dataclass
        # declaration
        names_now = {f.name for f in dataclasses.fields(type(msg))}
        if set(want) != names_now:
            failures.append(
                f"{name}: declared fields drifted: "
                f"{sorted(set(want) ^ names_now)}")
    # golden replay: frames archived by OLDER builds (e.g. pre-trace-id
    # layouts) must still DECODE — field values aren't compared (the new
    # fields default), only that the truncated-tail rule holds
    golden_dir = os.path.join(directory, "golden")
    golden = sorted(n for n in os.listdir(golden_dir)
                    if n.endswith(".frame")) \
        if os.path.isdir(golden_dir) else []
    for name in golden:
        try:
            with open(os.path.join(golden_dir, name), "rb") as f:
                raw = f.read()
            type_id, version, fixed, plen = _FRAME_HDR.unpack_from(raw, 0)
            off = _FRAME_HDR.size
            payload = raw[off:off + plen]
            off += plen
            (blen,) = struct.unpack_from("<I", raw, off)
            blob = raw[off + 4:off + 4 + blen] if blen else None
            decode_message(type_id, version, payload, blob, bool(fixed))
        except Exception as e:
            failures.append(f"golden/{name}: old frame no longer "
                            f"decodes: {e}")
    if failures:
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1
    print(f"{len(frames)} archived frames decode byte-exactly"
          + (f"; {len(golden)} golden old frames still decode"
             if golden else ""))
    return 0


class CoverageGap:
    """One FIXED message type missing one leg of its safety net."""

    __slots__ = ("type_name", "kind", "file", "line", "message")

    def __init__(self, type_name: str, kind: str, file: str, line: int,
                 message: str):
        self.type_name = type_name
        self.kind = kind  # "corpus" | "dencoder" | "golden"
        self.file = file
        self.line = line
        self.message = message


def _decl_site(cls) -> Tuple[str, int]:
    """(repo-relative file, line) a message class is declared at."""
    import inspect

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        src = inspect.getsourcefile(cls)
        _, line = inspect.getsourcelines(cls)
        return os.path.relpath(src, repo), line
    except (OSError, TypeError):
        return "corpus/wire", 1


def fixed_types() -> Dict[int, type]:
    """Registered message types with a FIXED binary layout (the
    data-plane set whose bytes the corpus pins).  Scoped to classes
    declared inside the ceph_tpu package: tests register fixture
    messages into the same process-global registry, and those are not
    wire ABI."""
    import ceph_tpu.mgr.daemon  # noqa: F401 — registers mgr types
    import ceph_tpu.rados.types  # noqa: F401 — registers the core set
    from ceph_tpu.rados.messenger import _MSG_TYPES

    return {tid: cls for tid, cls in _MSG_TYPES.items()
            if getattr(cls, "FIXED_FIELDS", None) is not None
            and cls.__module__.startswith("ceph_tpu.")}


def coverage_gaps(directory: str = CORPUS_DIR) -> List[CoverageGap]:
    """The coverage walk ``--strict`` and tpu-lint share: every FIXED
    type needs an archived frame, a dencoder round-trip, and (when
    versioned) a golden old-build frame."""
    from ceph_tpu.rados.messenger import decode_message, \
        encode_payload_parts

    gaps: List[CoverageGap] = []
    frames = set(os.listdir(directory)) if os.path.isdir(directory) \
        else set()
    golden_dir = os.path.join(directory, "golden")
    golden = set(os.listdir(golden_dir)) if os.path.isdir(golden_dir) \
        else set()
    for tid, cls in sorted(fixed_types().items()):
        name = cls.__name__
        file, line = _decl_site(cls)
        if not any(f == f"{name}.frame"
                   or (f.startswith(f"{name}.alt") and f.endswith(".frame"))
                   for f in frames):
            gaps.append(CoverageGap(
                name, "corpus", file, line,
                f"FIXED message {name} (id {tid}) has no archived frame "
                f"in corpus/wire — run `wire_corpus --create` after "
                f"adding it to _sample_messages()"))
        try:
            msg = cls()
            payload, blob, fixed = encode_payload_parts(msg)
            back = decode_message(
                tid, cls.VERSION, payload,
                None if blob is None else bytes(blob), fixed)
            if {k: _norm(v) for k, v in back.__dict__.items()} \
                    != {k: _norm(v) for k, v in msg.__dict__.items()}:
                raise ValueError("default instance did not round-trip "
                                 "field-identically")
        except Exception as e:
            gaps.append(CoverageGap(
                name, "dencoder", file, line,
                f"FIXED message {name} fails the dencoder round-trip: "
                f"{type(e).__name__}: {e}"))
        if cls.VERSION >= 2 and not any(
                f.startswith(f"{name}.") and f.endswith(".frame")
                for f in golden):
            gaps.append(CoverageGap(
                name, "golden", file, line,
                f"FIXED message {name} is v{cls.VERSION} but has no "
                f"golden old-build frame under corpus/wire/golden — "
                f"archive a pre-bump frame so the truncated-tail decode "
                f"rule stays replay-guarded"))
    return gaps


def check_strict(directory: str = CORPUS_DIR) -> int:
    gaps = coverage_gaps(directory)
    for g in gaps:
        print(f"FAIL {g.file}:{g.line}: [{g.kind}] {g.message}",
              file=sys.stderr)
    if not gaps:
        print(f"{len(fixed_types())} FIXED types fully covered "
              f"(corpus + dencoder + golden where versioned)")
    return 1 if gaps else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="wire-format corpus")
    p.add_argument("--create", action="store_true")
    p.add_argument("--check", action="store_true")
    p.add_argument("--strict", action="store_true",
                   help="with --check: also fail on FIXED types missing "
                        "corpus/dencoder/golden coverage")
    p.add_argument("--dir", default=CORPUS_DIR)
    args = p.parse_args(argv)
    if args.create:
        return create(args.dir)
    rc = check(args.dir)
    if args.strict:
        rc = check_strict(args.dir) or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
