"""cephfs-shell-lite: operator file access to a CephFS namespace
(reference src/tools/cephfs/shell/cephfs-shell: the non-FUSE client
surface).  One-shot commands over the cap-aware client:

    python -m ceph_tpu.tools.cephfs_shell --mon H:P --pool P ls /
    ... mkdir /dir | put LOCAL /remote | get /remote LOCAL | cat /f
    ... stat /f | chmod 600 /f | rm /f | mv /a /b | du /

The shell mounts (journal replay), runs the command through a
CephFSClient session, and unmounts (flushing write-behind) — so every
invocation observes and leaves a consistent namespace."""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="cephfs shell")
    p.add_argument("--mon", required=True, help="mon address host:port")
    p.add_argument("--pool", required=True, help="metadata/data pool")
    p.add_argument("--client", default="shell", help="client identity")
    sub = p.add_subparsers(dest="cmd", required=True)
    ls = sub.add_parser("ls")
    ls.add_argument("path", nargs="?", default="/")
    mk = sub.add_parser("mkdir")
    mk.add_argument("path")
    put = sub.add_parser("put")
    put.add_argument("local")
    put.add_argument("remote")
    get = sub.add_parser("get")
    get.add_argument("remote")
    get.add_argument("local")
    cat = sub.add_parser("cat")
    cat.add_argument("path")
    st = sub.add_parser("stat")
    st.add_argument("path")
    ch = sub.add_parser("chmod")
    ch.add_argument("mode", help="octal, e.g. 600")
    ch.add_argument("path")
    rm = sub.add_parser("rm")
    rm.add_argument("path")
    mv = sub.add_parser("mv")
    mv.add_argument("src")
    mv.add_argument("dst")
    du = sub.add_parser("du")
    du.add_argument("path", nargs="?", default="/")
    return p.parse_args(argv)


async def _du(client, path: str) -> int:
    """Recursive byte total (file sizes from dentries, no data reads)."""
    total = 0
    st = await client.stat(path)
    if st.get("type") != "dir":
        return int(st.get("size", 0))
    for name in await client.listdir(path):
        child = path.rstrip("/") + "/" + name
        total += await _du(client, child)
    return total


async def run(args) -> int:
    from ceph_tpu.rados.librados import Rados
    from ceph_tpu.services.mds import (CephFSClient, FileSystem, FsError,
                                       MDSServer)

    host, port = args.mon.rsplit(":", 1)
    rados = await Rados((host, int(port))).connect()
    try:
        io = await rados.open_ioctx(args.pool)
        fs = FileSystem(io)
        await fs.mount()  # journal replay: the up:replay stage
        client = CephFSClient(MDSServer(fs), args.client)
        try:
            if args.cmd == "ls":
                for name in await client.listdir(args.path):
                    print(name)
            elif args.cmd == "mkdir":
                await client.mkdir(args.path)
            elif args.cmd == "put":
                with open(args.local, "rb") as f:
                    data = f.read()
                async with await client.open(args.remote, "w") as fh:
                    await fh.write(data)
                print(f"wrote {len(data)} bytes to {args.remote}")
            elif args.cmd == "get":
                async with await client.open(args.remote, "r") as fh:
                    data = await fh.read()
                with open(args.local, "wb") as f:
                    f.write(data)
                print(f"read {len(data)} bytes from {args.remote}")
            elif args.cmd == "cat":
                async with await client.open(args.path, "r") as fh:
                    sys.stdout.buffer.write(await fh.read())
            elif args.cmd == "stat":
                st = await client.stat(args.path)
                if "mode" in st:
                    st = dict(st, mode=oct(st["mode"]))
                print(json.dumps(st, indent=1, sort_keys=True))
            elif args.cmd == "chmod":
                await client.chmod(args.path, int(args.mode, 8))
            elif args.cmd == "rm":
                await client.unlink(args.path)
            elif args.cmd == "mv":
                await client.rename(args.src, args.dst)
            elif args.cmd == "du":
                print(await _du(client, args.path))
            return 0
        except (FsError, OSError, ValueError) as e:
            # one error contract: message + exit 1, never a traceback
            # (OSError: local file I/O; ValueError: e.g. a bad octal)
            print(str(e), file=sys.stderr)
            return 1
        finally:
            await client.unmount()  # flush write-behind, drop caps
    finally:
        await rados.shutdown()


def main(argv=None) -> int:
    return asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
