"""registry checker family: name registries that only fail at runtime.

Three registries where a typo'd string ships silently and explodes (or
worse, silently defaults) in production:

- config keys: every constant key passed to ``conf.get``/``conf.set``
  must be declared in ``common/config.py``'s ``DEFAULT_SCHEMA`` (the
  Config class accepts unknown keys as passthrough, so a misspelled
  option reads its fallback default forever); and the reverse — a
  schema option no code ever reads is dead weight that operators will
  set to no effect.  Dynamic ``conf.get(f"prefix_{x}")`` families are
  honored by composition: an option counts as referenced when a dynamic
  prefix matches AND the remaining suffix appears as a string constant
  somewhere in the tree (so ``osd_{key}`` + ``"hit_set_period"`` covers
  ``osd_hit_set_period`` without whitelisting every osd_* option).
- perf counters: every counter name bumped via
  ``inc/dec/tinc/hinc/time_avg`` must be declared by some
  ``PerfCountersBuilder.add_*`` or ``PerfCounters.ensure`` call —
  bumping an undeclared counter raises ``KeyError`` on the hot path,
  but only on the first traversal of that path.
- asok commands: every key in ``tools/ceph.py``'s ``ASOK_RENDERERS``
  must match a command some daemon actually registers (a renamed
  command silently orphans its renderer — the ``ceph daemon``/``ceph
  tell`` output degrades to raw JSON with no test failing).  Commands
  WITHOUT a custom renderer are fine: ``print_asok_result``'s JSON
  fallback is the default renderer for every registered command.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.tools.lint.findings import Finding

CONFIG_REL = os.path.join("ceph_tpu", "common", "config.py")
CEPH_TOOL_REL = os.path.join("ceph_tpu", "tools", "ceph.py")

_PERF_DECL = {"add_u64", "add_u64_counter", "add_time_avg",
              "add_histogram", "ensure"}
_PERF_USE = {"inc", "dec", "tinc", "hinc", "time_avg"}
# receivers that denote THE config object (rgw's plain `cfg` dicts and
# arbitrary dict.get sites must not match)
_CONF_RECV = re.compile(r"(^|\.)conf(ig)?$")


def check(root: str, sources: List[Tuple[str, str]]) -> List[Finding]:
    findings: List[Finding] = []
    schema, schema_lines = _parse_schema(root)
    if schema is None:
        return findings  # no config module in scanned tree (test trees)

    # Registry coherence is a WHOLE-TREE property: a counter declared in
    # osd.py legitimizes a bump in scheduler.py.  A path-scoped run
    # (pre-commit on one file) must therefore build the reference pools
    # from the full tree — scanned sources win (tests feed doctored
    # copies), everything else loads from disk — while per-site findings
    # are still emitted only for the files actually scanned.
    scanned = {relpath for relpath, _ in sources}
    global_sources = list(sources)
    tree_dir = os.path.join(root, "ceph_tpu")
    if os.path.isdir(tree_dir):
        for dirpath, dirnames, files in os.walk(tree_dir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                if rel not in scanned:
                    try:
                        with open(os.path.join(dirpath, fn),
                                  encoding="utf-8") as fh:
                            global_sources.append((rel, fh.read()))
                    except (OSError, UnicodeDecodeError):
                        pass

    conf_refs: List[Tuple[str, int, str]] = []   # (file, line, key)
    dyn_prefixes: Set[str] = set()
    perf_decl: Set[str] = set()
    perf_use: List[Tuple[str, int, str]] = []
    asok_cmds: Set[str] = set()
    renderers: List[Tuple[str, int, str]] = []
    all_constants: Set[str] = set()

    for relpath, text in global_sources:
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        is_config = relpath.replace("/", os.sep) == CONFIG_REL
        for node in ast.walk(tree):
            # config.py's own Option("name") literals must not count as
            # references, or no option could ever be dead
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                             str) \
                    and not is_config:
                all_constants.add(node.value)
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            recv = ast.unparse(func.value)
            if func.attr in ("get", "set") and not is_config \
                    and _CONF_RECV.search(recv) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    conf_refs.append((relpath, node.lineno, arg.value))
                elif isinstance(arg, ast.JoinedStr) and arg.values \
                        and isinstance(arg.values[0], ast.Constant):
                    dyn_prefixes.add(str(arg.values[0].value))
            if func.attr in _PERF_DECL and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    perf_decl.add(arg.value)
            if func.attr in _PERF_USE and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    perf_use.append((relpath, node.lineno, arg.value))
            if func.attr == "register" and node.args and "asok" in recv:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    asok_cmds.add(arg.value)
        if relpath.replace("/", os.sep) == CEPH_TOOL_REL:
            renderers = _renderer_keys(tree, relpath)
        if relpath.replace("/", os.sep) == os.path.join(
                "ceph_tpu", "common", "admin_socket.py"):
            # AdminSocket's built-in self.register(...) commands
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "register" and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    asok_cmds.add(node.args[0].value)

    # -- config: referenced key must exist in the schema ---------------------
    for relpath, line, key in conf_refs:
        if relpath not in scanned:
            continue
        if key not in schema:
            findings.append(Finding(
                check="registry/unknown-config-key", file=relpath,
                line=line, key=key,
                message=f"config key {key!r} is not declared in "
                        f"common/config.py DEFAULT_SCHEMA — unknown keys "
                        f"read as untyped passthrough, so a typo silently "
                        f"returns the call-site fallback forever"))

    # -- config: schema option must be referenced somewhere ------------------
    # tests count as references for the dead-option direction (injection
    # and CI-gate options are legitimately exercised only from tests)
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        lit = re.compile(r"""["']([A-Za-z0-9_.:-]+)["']""")
        for fn in os.listdir(tests_dir):
            if fn.endswith(".py"):
                with open(os.path.join(tests_dir, fn),
                          encoding="utf-8") as fh:
                    all_constants.update(lit.findall(fh.read()))
    referenced = {k for _, _, k in conf_refs}
    # dead-option findings belong to config.py: only a run that scans it
    # may emit them (a one-file pre-commit run must stay quiet)
    config_scanned = CONFIG_REL in {r.replace("/", os.sep)
                                    for r in scanned}
    for opt in (sorted(schema) if config_scanned else ()):
        if opt in referenced or opt in all_constants:
            continue
        if any(opt.startswith(p) and opt[len(p):] in all_constants
               for p in dyn_prefixes):
            continue  # dynamic prefix + constant suffix composition
        findings.append(Finding(
            check="registry/dead-config-option", file=CONFIG_REL,
            line=schema_lines.get(opt, 1), key=opt,
            message=f"schema option {opt!r} is never read by any code "
                    f"path — operators setting it get silent no-ops; "
                    f"wire it up or remove the declaration"))

    # -- perf counters -------------------------------------------------------
    for relpath, line, name in perf_use:
        if relpath not in scanned:
            continue
        if name not in perf_decl:
            findings.append(Finding(
                check="registry/undeclared-perf-counter", file=relpath,
                line=line, key=name,
                message=f"perf counter {name!r} is bumped but never "
                        f"declared by any PerfCountersBuilder.add_* / "
                        f"ensure() — first traversal of this path raises "
                        f"KeyError"))

    # -- asok renderers ------------------------------------------------------
    for relpath, line, key in renderers:
        if relpath not in scanned:
            continue
        if key not in asok_cmds:
            findings.append(Finding(
                check="registry/orphan-asok-renderer", file=relpath,
                line=line, key=key,
                message=f"ASOK_RENDERERS[{key!r}] matches no registered "
                        f"admin-socket command — a renamed command "
                        f"silently degrades `ceph daemon/tell` output to "
                        f"the raw-JSON fallback"))
    return findings


def _parse_schema(root: str
                  ) -> Tuple[Optional[Set[str]], Dict[str, int]]:
    path = os.path.join(root, CONFIG_REL)
    if not os.path.exists(path):
        return None, {}
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    names: Set[str] = set()
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "Option" and node.args
                and isinstance(node.args[0], ast.Constant)):
            names.add(node.args[0].value)
            lines[node.args[0].value] = node.lineno
    return names, lines


def _renderer_keys(tree: ast.AST, relpath: str
                   ) -> List[Tuple[str, int, str]]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "ASOK_RENDERERS" \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append((relpath, k.lineno, k.value))
    return out
