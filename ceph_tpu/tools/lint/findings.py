"""Finding + suppression-baseline model for tpu-lint.

A finding's identity is ``(check, file, key)`` — deliberately NOT the
line number, so unrelated edits above a baselined site don't stale the
suppression.  ``key`` is the checker-chosen stable handle (a message
name, a config key, a lock name...).  Baseline entries are committed in
``baseline.json`` and every one must carry a non-empty one-line reason;
the lint driver turns entries that suppress nothing into findings, so
the file can only shrink (the reference analog: a suppressions file that
rots is worse than none).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Finding:
    check: str      # family/rule, e.g. "wire-abi/layout-break"
    file: str       # repo-relative path
    line: int
    key: str        # stable identity within (check, file)
    message: str
    suppressed_reason: Optional[str] = None

    @property
    def ident(self) -> str:
        return f"{self.check}::{self.file}::{self.key}"

    def to_json(self) -> Dict:
        out = {"check": self.check, "file": self.file, "line": self.line,
               "key": self.key, "message": self.message}
        if self.suppressed_reason:
            out["suppressed_reason"] = self.suppressed_reason
        return out

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.check}] {self.message}"


@dataclass
class BaselineEntry:
    check: str
    file: str
    key: str
    reason: str

    @property
    def ident(self) -> str:
        return f"{self.check}::{self.file}::{self.key}"


@dataclass
class Baseline:
    entries: List[BaselineEntry] = field(default_factory=list)

    @staticmethod
    def key_of(f: Finding) -> str:
        return f.ident

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as fh:
            raw = json.load(fh)
        entries = []
        for e in raw.get("suppressions", []):
            reason = (e.get("reason") or "").strip()
            if not reason:
                raise ValueError(
                    f"baseline entry {e.get('check')}::{e.get('file')}::"
                    f"{e.get('key')} has no reason — every suppression "
                    f"must carry a one-line justification")
            entries.append(BaselineEntry(check=e["check"], file=e["file"],
                                         key=e["key"], reason=reason))
        return cls(entries)

    def match(self, f: Finding) -> Optional[str]:
        for e in self.entries:
            if (e.check == f.check and e.file == f.file
                    and e.key == f.key):
                return e.reason
        return None

    def save(self, path: str) -> None:
        data = {"suppressions": [
            {"check": e.check, "file": e.file, "key": e.key,
             "reason": e.reason}
            for e in sorted(self.entries,
                            key=lambda e: (e.check, e.file, e.key))]}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
            fh.write("\n")
