"""CLI driver: ``python -m ceph_tpu.tools.lint``.

Exit status is the contract (CI gates on it): 0 when every finding is
fixed or baselined, 1 otherwise.  ``--json`` emits the machine-readable
findings document; ``--update-wire-lock`` regenerates
``corpus/wire/ABI.lock`` from the current declarations (the sanctioned
wire-change workflow, see README "Static analysis & sanitizers");
``--update-baseline`` rewrites the suppression baseline from the current
findings with TODO reasons that a human must replace before commit (the
baseline loader rejects empty reasons).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ceph_tpu.tools.lint import (BASELINE_PATH, CHECK_FAMILIES, REPO_ROOT,
                                 WIRE_LOCK_PATH, run_lint)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ceph_tpu.tools.lint",
        description="project-invariant static analysis for ceph_tpu")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: ceph_tpu/)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--checks", default=",".join(CHECK_FAMILIES),
                   help=f"comma-separated families "
                        f"(default: {','.join(CHECK_FAMILIES)})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--update-wire-lock", action="store_true",
                   help="regenerate corpus/wire/ABI.lock from the "
                        "current message declarations and exit")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite baseline.json from current findings "
                        "(reasons left as TODO for a human)")
    p.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.update_wire_lock:
        from ceph_tpu.tools.lint import wire_abi

        sources = []
        for rel in wire_abi.WIRE_SOURCES:
            path = os.path.join(args.root, rel)
            if os.path.exists(path):
                with open(path, encoding="utf-8") as fh:
                    sources.append((rel, fh.read()))
        decls = wire_abi.extract(sources)
        lock_path = os.path.join(args.root, "corpus", "wire", "ABI.lock") \
            if args.root != REPO_ROOT else WIRE_LOCK_PATH
        wire_abi.write_lock(lock_path, decls)
        print(f"wire-ABI lockfile written: {len(decls)} messages -> "
              f"{lock_path}")
        return 0

    checks = tuple(c.strip() for c in args.checks.split(",") if c.strip())
    unknown = [c for c in checks if c not in CHECK_FAMILIES]
    if unknown:
        print(f"unknown check families: {unknown} "
              f"(have: {list(CHECK_FAMILIES)})", file=sys.stderr)
        return 2

    report = run_lint(
        root=args.root,
        paths=[os.path.abspath(p) for p in args.paths] or None,
        checks=checks,
        baseline_path=None if args.no_baseline else BASELINE_PATH,
    )

    if args.update_baseline:
        from ceph_tpu.tools.lint.findings import Baseline, BaselineEntry

        # only a FULL run may rewrite the baseline: a --checks subset or
        # path-scoped run cannot judge entries outside its scope, and
        # dropping them would destroy hand-written justifications
        if args.paths or set(checks) != set(CHECK_FAMILIES) \
                or args.no_baseline:
            print("--update-baseline requires a full run (no paths, all "
                  "check families, baseline enabled)", file=sys.stderr)
            return 2
        # MERGE, never rewrite-from-scratch: existing entries that still
        # suppress something keep their hand-written reasons; only NEW
        # findings gain TODO entries.  (Stale entries — suppressing
        # nothing — are dropped, which is what their finding demands.)
        old = Baseline.load(BASELINE_PATH)
        kept_idents = {f.ident for f in report.suppressed}
        entries = [e for e in old.entries if e.ident in kept_idents]
        entries += [BaselineEntry(
            check=f.check, file=f.file, key=f.key,
            reason="TODO: justify this suppression in one line")
            for f in report.findings
            if not f.check.startswith("baseline/")]
        Baseline(entries).save(BASELINE_PATH)
        n_new = len(entries) - len([e for e in entries
                                    if e.ident in kept_idents])
        print(f"baseline now has {len(entries)} entries "
              f"({n_new} new with TODO reasons — replace them before "
              f"committing)")
        return 0

    if args.json:
        print(json.dumps(report.to_json(), indent=1))
    else:
        for f in report.findings:
            print(f"FAIL {f.render()}", file=sys.stderr)
        n_sup = len(report.suppressed)
        print(f"tpu-lint: {report.files_scanned} files, "
              f"{len(report.findings)} finding(s)"
              + (f", {n_sup} baselined" if n_sup else ""))
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
