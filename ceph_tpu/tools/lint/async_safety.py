"""async-safety checker family.

The reactor/messenger plane mixes asyncio event loops with real threads
(reactor workers, the BatchingQueue dispatcher, native calls), which is
exactly where review keeps catching the same three defects:

- ``blocking-call``: a synchronous blocker (``time.sleep``, subprocess,
  a blocking ``threading.Lock.acquire``) inside an ``async def`` stalls
  the WHOLE event loop — every connection, heartbeat and timer on it;
- ``lock-across-await``: a ``with <thread-lock>:`` block containing an
  ``await`` parks the lock across a suspension point, so any OTHER task
  or thread contending for it deadlocks the loop (asyncio locks use
  ``async with``; thread locks must be released before awaiting);
- ``cross-loop-call``: calling ``loop.call_soon``/``create_task`` on a
  STORED loop from sync code may run on a foreign thread — the home-loop
  idiom is ``call_soon_threadsafe`` (messenger.py/reactor.py hop this
  way everywhere; this checker keeps it that way);
- ``shm-ring-payload`` (cross-process seam): objects queued onto a
  shared-memory ring (ShmRingPipe ``put_record``/``send_bytes``/
  ``send_gather``) must be WIRE BYTES or fixed-layout packs — a live
  message/connection/loop/lock object cannot cross a fork, and a
  reference pushed into shm is silently a different object on the far
  side.  Flagged: a bare object-ish name (``msg``, ``conn``, ``loop``,
  ``lock``, ``task``, ``sock`` ...) or ``self`` passed as a ring
  payload element;
- ``shm-lifecycle`` (cross-process seam): a module that opens
  ``multiprocessing.shared_memory.SharedMemory`` must pair it with both
  ``.close()`` and ``.unlink()`` on some teardown path — a missing
  close leaks the mapping, a missing unlink leaks /dev/shm segments
  past every process's death.

Heuristic exemptions (calibrated on the shipped tree):

- ``asyncio.get_running_loop().create_task(...)`` and locals assigned
  from an expression containing ``get_running_loop`` are loop-correct by
  construction (``get_running_loop`` raises off-loop, it cannot cross);
- calls wrapped in an argument to ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe`` ARE the idiom, not a violation;
- ``await x.acquire()`` is an asyncio acquire; only the non-awaited,
  argument-less form is flagged.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from ceph_tpu.tools.lint.findings import Finding

# sync calls that block the calling thread (and with it, the loop)
_BLOCKING = {
    "time.sleep": "blocks the event loop; use `await asyncio.sleep`",
    "os.system": "blocks the event loop; use an executor",
    "subprocess.run": "blocks the event loop; use "
                      "`asyncio.create_subprocess_exec` or an executor",
    "subprocess.call": "blocks the event loop",
    "subprocess.check_call": "blocks the event loop",
    "subprocess.check_output": "blocks the event loop",
    "socket.create_connection": "blocks the event loop; use "
                                "`asyncio.open_connection`",
}

_LOOP_METHODS = {"call_soon", "call_later", "call_at", "create_task"}
_THREADSAFE = {"call_soon_threadsafe", "run_coroutine_threadsafe"}

# shm ring producer surface (shm_ring.ShmRingPipe / ShmConnEndpoint):
# payload elements must be byte-plane values, never live objects
_SHM_PUT = {"put_record", "send_bytes", "send_gather"}
# bare names that denote live runtime objects on the wrong side of a
# fork (heuristic, like _LOCKISH: calibrated on the shipped tree)
_OBJECTISH = re.compile(
    r"^(msg|message|conn|connection|loop|lock|mutex|task|future|sock"
    r"|socket|worker|group|self)$")


_LOCKISH = re.compile(r"(^|[^a-z])(lock|mutex)")


def _lockish(src: str) -> bool:
    # word-start match: `self._lock`, `lock`, `shard_lock` hit;
    # `block`, `self.blocked`, `unlock` (the 'l' follows a letter) miss
    return _LOCKISH.search(src.lower()) is not None


class _Scanner(ast.NodeVisitor):
    def __init__(self, relpath: str, findings: List[Finding]):
        self.relpath = relpath
        self.findings = findings
        # stack of (is_async, get_running_loop_locals, node)
        self.funcs: List[Tuple[bool, set, ast.AST]] = []
        self.threadsafe_depth = 0
        self.await_depth = 0

    # -- function scopes -----------------------------------------------------

    def _visit_func(self, node, is_async: bool) -> None:
        loop_locals = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and "get_running_loop" in ast.unparse(sub.value):
                loop_locals.add(sub.targets[0].id)
        self.funcs.append((is_async, loop_locals, node))
        self.generic_visit(node)
        self.funcs.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node, False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node, True)

    def visit_Lambda(self, node):
        # a lambda inherits its enclosing context (it runs wherever it is
        # called; for the threadsafe-wrap exemption the wrap matters)
        self.generic_visit(node)

    @property
    def in_async(self) -> bool:
        return bool(self.funcs) and self.funcs[-1][0]

    # -- await tracking (awaited calls are not blocking) ---------------------

    def visit_Await(self, node):
        self.await_depth += 1
        self.generic_visit(node)
        self.await_depth -= 1

    # -- calls ---------------------------------------------------------------

    def visit_Call(self, node):
        func = node.func
        dotted = ""
        if isinstance(func, (ast.Attribute, ast.Name)):
            try:
                dotted = ast.unparse(func)
            except Exception:  # pragma: no cover - unparse is total here
                dotted = ""

        if self.in_async:
            self._check_blocking(node, func, dotted)

        if isinstance(func, ast.Attribute) and func.attr in _THREADSAFE:
            self.threadsafe_depth += 1
            self.generic_visit(node)
            self.threadsafe_depth -= 1
            return

        if isinstance(func, ast.Attribute) and func.attr in _LOOP_METHODS:
            self._check_cross_loop(node, func)

        if isinstance(func, ast.Attribute) and func.attr in _SHM_PUT:
            self._check_shm_payload(node, func)

        self.generic_visit(node)

    def _check_shm_payload(self, node, func: ast.Attribute) -> None:
        """Cross-process seam: ring payload elements must be byte-plane
        values.  put_record(kind, parts) / send_gather(wp, parts) carry
        the payload LAST; send_bytes(parts) carries it first."""
        if not node.args:
            return
        payload = node.args[-1]
        elements = []
        if isinstance(payload, (ast.List, ast.Tuple)):
            for e in payload.elts:
                elements.append(e.value if isinstance(e, ast.Starred)
                                else e)
        else:
            elements.append(payload)
        for e in elements:
            name = e.id if isinstance(e, ast.Name) else None
            if name is not None and _OBJECTISH.match(name):
                self.findings.append(Finding(
                    check="async-safety/shm-ring-payload",
                    file=self.relpath, line=node.lineno,
                    key=f"{func.attr}:{name}@L{node.lineno}",
                    message=f"`{name}` queued onto a shared-memory ring "
                            f"via `{func.attr}` in {self._func_name()}: "
                            f"only wire-frame bytes / fixed-layout packs "
                            f"may cross the process seam — a live "
                            f"object reference is a DIFFERENT object on "
                            f"the far side of the fork (serialize to "
                            f"bytes first)"))

    def _check_blocking(self, node, func, dotted: str) -> None:
        for pat, why in _BLOCKING.items():
            if dotted == pat or dotted.endswith("." + pat):
                self.findings.append(Finding(
                    check="async-safety/blocking-call", file=self.relpath,
                    line=node.lineno, key=f"{pat}@L{node.lineno}",
                    message=f"`{pat}` inside `async def` "
                            f"{self._func_name()}: {why}"))
                return
        # blocking .acquire() on a lock-looking receiver, not awaited:
        # a threading lock acquire would park the whole loop
        if (isinstance(func, ast.Attribute) and func.attr == "acquire"
                and not node.args and not node.keywords
                and self.await_depth == 0
                and _lockish(ast.unparse(func.value))):
            self.findings.append(Finding(
                check="async-safety/blocking-call", file=self.relpath,
                line=node.lineno,
                key=f"acquire:{ast.unparse(func.value)}@L{node.lineno}",
                message=f"non-awaited blocking "
                        f"`{ast.unparse(func.value)}.acquire()` inside "
                        f"`async def` {self._func_name()}: a thread-lock "
                        f"acquire stalls the event loop (await an asyncio "
                        f"lock, or release before suspension)"))

    def _check_cross_loop(self, node, func: ast.Attribute) -> None:
        if self.threadsafe_depth:
            return  # wrapped in call_soon_threadsafe(...): the idiom
        if self.in_async:
            return  # on-loop by definition (async bodies run in the loop)
        recv = ast.unparse(func.value)
        if recv.startswith("asyncio"):
            return  # asyncio.get_running_loop()/asyncio.ensure_future
        if self.funcs and isinstance(func.value, ast.Name) \
                and func.value.id in self.funcs[-1][1]:
            return  # local assigned from get_running_loop: on-loop
        self.findings.append(Finding(
            check="async-safety/cross-loop-call", file=self.relpath,
            line=node.lineno, key=f"{recv}.{func.attr}@L{node.lineno}",
            message=f"`{recv}.{func.attr}(...)` from sync code in "
                    f"{self._func_name()}: a stored loop may be homed on "
                    f"another thread — use "
                    f"`{recv}.call_soon_threadsafe(...)` (the "
                    f"messenger/reactor home-loop idiom) or prove the "
                    f"caller is on that loop via "
                    f"`asyncio.get_running_loop()`"))

    # -- with blocks ---------------------------------------------------------

    def visit_With(self, node):
        has_await = any(isinstance(x, (ast.Await, ast.AsyncFor,
                                       ast.AsyncWith))
                        for x in ast.walk(node))
        if has_await:
            for item in node.items:
                src = ast.unparse(item.context_expr)
                if _lockish(src):
                    self.findings.append(Finding(
                        check="async-safety/lock-across-await",
                        file=self.relpath, line=node.lineno,
                        key=f"{src}@L{node.lineno}",
                        message=f"thread lock `{src}` held across an "
                                f"`await` in {self._func_name()}: any "
                                f"other thread or task contending for it "
                                f"deadlocks against the suspended task "
                                f"(narrow the critical section, or use "
                                f"`async with` on an asyncio lock)"))
        self.generic_visit(node)

    def _func_name(self) -> str:
        for is_async, _, node in reversed(self.funcs):
            if hasattr(node, "name"):
                return f"`{node.name}`"
        return "<module>"


_SHM_OPEN = re.compile(r"\bSharedMemory\s*\(")


def _check_shm_lifecycle(relpath: str, text: str,
                         findings: List[Finding]) -> None:
    """A module opening SharedMemory must pair it with close AND unlink
    somewhere on its teardown paths (the /dev/shm segment outlives
    every process until SOMEONE unlinks; the mapping leaks until
    someone closes)."""
    m = _SHM_OPEN.search(text)
    if m is None:
        return
    missing = [what for what, pat in (("close", ".close("),
                                      ("unlink", ".unlink("))
               if pat not in text]
    if missing:
        line = text[:m.start()].count("\n") + 1
        findings.append(Finding(
            check="async-safety/shm-lifecycle", file=relpath, line=line,
            key=f"shm-lifecycle:{'+'.join(missing)}",
            message=f"`SharedMemory(` opened with no paired "
                    f"{' / '.join('.' + w + '()' for w in missing)} in "
                    f"this module: shared-memory segments outlive every "
                    f"process until unlinked, and mappings leak until "
                    f"closed — add the teardown pair "
                    f"(creator unlinks, both ends close)"))


def check(sources: List[Tuple[str, str]]) -> List[Finding]:
    findings: List[Finding] = []
    for relpath, text in sources:
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue  # codec family reports unparsable files
        _Scanner(relpath, findings).visit(tree)
        _check_shm_lifecycle(relpath, text, findings)
    return findings
