"""tpu-lint: project-invariant static analysis for the ceph_tpu tree.

The runtime half of these invariants already exists (``common/lockdep``
for lock-order cycles, ``wire_corpus --check`` for archived frames); this
package is the static half (reference: the tree the paper mirrors enforces
them with src/common/lockdep.cc, ceph-dencoder round-trips and
debug-build asserts).  Four checker families:

- ``wire-abi``    — wire ids + FIXED_FIELDS layouts vs the committed
                    lockfile ``corpus/wire/ABI.lock`` (append-only tails,
                    no id reuse, corpus/dencoder/golden coverage)
- ``async-safety``— blocking calls in ``async def`` bodies, thread locks
                    held across ``await``, raw cross-loop calls that
                    bypass ``call_soon_threadsafe``
- ``registry``    — config keys vs the ``common/config.py`` schema (both
                    directions), perf-counter bumps vs declarations, asok
                    renderer/command coherence
- ``codec``       — struct format strings vs argument counts, FIXED
                    layout hygiene (declared fields, defaults for the
                    truncated-tail decode rule, known kind codes)

Entry point::

    python -m ceph_tpu.tools.lint            # exit 0 = clean/baselined
    python -m ceph_tpu.tools.lint --json     # machine-readable findings

Findings are suppressed per-finding via ``baseline.json`` next to this
file; every entry carries a one-line justification and a stale entry
(suppressing nothing) is itself a finding, so the baseline can only
shrink.  ``--update-wire-lock`` regenerates the ABI lockfile — the one
sanctioned way to land an (append-only) wire layout change.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.tools.lint.findings import Baseline, Finding

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")
WIRE_LOCK_PATH = os.path.join(REPO_ROOT, "corpus", "wire", "ABI.lock")

CHECK_FAMILIES = ("wire-abi", "async-safety", "registry", "codec")


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
        }


def _py_files(root: str, paths: Optional[List[str]]) -> List[str]:
    out = []
    for base in (paths or [os.path.join(root, "ceph_tpu")]):
        if os.path.isfile(base):
            out.append(base)
            continue
        for dirpath, dirnames, files in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(os.path.join(dirpath, f)
                       for f in files if f.endswith(".py"))
    return sorted(out)


def run_lint(
    root: str = REPO_ROOT,
    paths: Optional[List[str]] = None,
    checks: Tuple[str, ...] = CHECK_FAMILIES,
    baseline_path: Optional[str] = BASELINE_PATH,
    wire_lock_path: str = WIRE_LOCK_PATH,
    wire_sources: Optional[List[Tuple[str, str]]] = None,
    corpus_dir: Optional[str] = None,
    coverage: bool = True,
) -> LintReport:
    """Run the checker families over the tree and fold the baseline in.

    ``wire_sources`` overrides the scanned (path, source-text) pairs for
    the wire-ABI family — tests feed doctored copies of ``types.py``
    through the real committed lockfile.  ``coverage=False`` skips the
    runtime corpus-coverage walk (pure-AST mode).
    """
    from ceph_tpu.tools.lint import async_safety, codec, registry, wire_abi

    files = _py_files(root, paths)
    sources: List[Tuple[str, str]] = []
    for p in files:
        try:
            with open(p, encoding="utf-8") as f:
                sources.append((os.path.relpath(p, root), f.read()))
        except (OSError, UnicodeDecodeError):
            sources.append((os.path.relpath(p, root), ""))

    findings: List[Finding] = []
    if "wire-abi" in checks:
        findings += wire_abi.check(
            root, lock_path=wire_lock_path, sources=wire_sources,
            corpus_dir=corpus_dir, coverage=coverage)
    if "async-safety" in checks:
        findings += async_safety.check(sources)
    if "registry" in checks:
        findings += registry.check(root, sources)
    if "codec" in checks:
        findings += codec.check(sources, wire_sources=wire_sources)

    report = LintReport(files_scanned=len(files))
    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()
    used = set()
    for f in findings:
        reason = baseline.match(f)
        if reason is not None:
            f.suppressed_reason = reason
            used.add(baseline.key_of(f))
            report.suppressed.append(f)
        else:
            report.findings.append(f)
    # a baseline entry that no longer suppresses anything is stale: the
    # defect was fixed (delete the entry) or the identity drifted (the
    # suppression silently stopped protecting) — either way, a finding.
    # Likewise an --update-baseline TODO reason left in place: the
    # suppression works, but an unjustified one must not pass CI.
    bl_rel = os.path.relpath(baseline_path or BASELINE_PATH, root)
    scanned_files = {rel for rel, _ in sources}
    full_scope = paths is None
    for entry in baseline.entries:
        # entries of families that did not run this invocation, or (on a
        # path-scoped run) whose file was not scanned, cannot be judged
        # stale — a --checks subset or one-file pre-commit run must not
        # demand removal of a suppression the full run still needs.  A
        # FULL run judges unscanned files too: there, an entry naming a
        # file that no longer exists IS the classic stale case.
        if entry.check.split("/", 1)[0] not in checks:
            continue
        if not full_scope and entry.file not in scanned_files:
            continue
        if entry.ident not in used:
            report.findings.append(Finding(
                check="baseline/stale", file=bl_rel, line=1,
                key=entry.key,
                message=f"baseline entry suppresses nothing: {entry.key!r} "
                        f"(reason: {entry.reason}) — remove it",
            ))
        elif entry.reason.lower().startswith("todo"):
            report.findings.append(Finding(
                check="baseline/unjustified", file=bl_rel, line=1,
                key=entry.key,
                message=f"baseline entry {entry.key!r} still carries the "
                        f"--update-baseline TODO reason — write the real "
                        f"one-line justification",
            ))
    report.findings.sort(key=lambda f: (f.check, f.file, f.line, f.key))
    return report
