"""wire-abi checker family: the message registry vs the committed
lockfile ``corpus/wire/ABI.lock``.

The wire contract this tree lives by (types.py FIXED_FIELDS comments,
r11/r13/r15 golden frames) is append-only: a FIXED message may only GROW
at the tail, with a version bump, and old frames must keep decoding via
the truncated-tail rule.  Review discipline enforced that for fifteen
rounds; this checker enforces it mechanically:

- every ``@message(id[, version=v])`` class is extracted from source by
  AST (no imports — a doctored tree that would not even import still
  gets checked), along with its FIXED_FIELDS layout wherever declared
  (class body or module-level ``Cls.FIXED_FIELDS = [...]``);
- duplicate wire ids are an error even across files (the runtime
  registry only catches collisions that actually import together);
- against the lockfile: removed messages, reused/changed ids, version
  regressions, any non-append layout change (insert, reorder, rename,
  kind change, removal), and a grown tail without a version bump all
  fail;
- messages absent from the lockfile fail with ``wire-abi/unlocked`` —
  ``python -m ceph_tpu.tools.lint --update-wire-lock`` is the one
  sanctioned way to commit a layout change, which makes every wire
  evolution an explicit, reviewable diff of ABI.lock;
- coverage: every FIXED message must be archived in corpus/wire, must
  round-trip through the dencoder, and (when version >= 2) must have a
  golden old-build frame — delegated to
  ``wire_corpus.coverage_gaps()`` so ``wire_corpus --check --strict``
  and the lint share one implementation of the walk.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.tools.lint.findings import Finding

# the modules that declare wire messages (repo-relative); FIXED_FIELDS
# assigned outside these files would be invisible, so codec hygiene also
# checks no other file assigns one
WIRE_SOURCES = (
    os.path.join("ceph_tpu", "rados", "types.py"),
    os.path.join("ceph_tpu", "rados", "messenger.py"),
    os.path.join("ceph_tpu", "mgr", "daemon.py"),
)

VALID_KINDS = {"q", "Q", "d", "?", "s", "y", "Q*", "s*", "qq*", "addr"}


@dataclass
class MsgDecl:
    name: str
    file: str
    line: int
    type_id: int
    version: int
    fixed_fields: Optional[List[Tuple[str, str]]] = None
    fixed_line: int = 0
    # dataclass field names declared in the class body, in order, with
    # whether each carries a default (the truncated-tail rule needs one)
    fields: List[Tuple[str, bool]] = field(default_factory=list)


def _literal_fields(node: ast.AST) -> Optional[List[Tuple[str, str]]]:
    """Evaluate a FIXED_FIELDS literal: a list of (name, kind) tuples."""
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if not isinstance(val, list):
        return None
    out = []
    for item in val:
        if (not isinstance(item, tuple) or len(item) != 2
                or not all(isinstance(x, str) for x in item)):
            return None
        out.append((item[0], item[1]))
    return out


def extract(sources: List[Tuple[str, str]]) -> List[MsgDecl]:
    """(relpath, source) pairs -> message declarations, in file order."""
    decls: List[MsgDecl] = []
    for relpath, text in sources:
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue  # codec family reports unparsable files
        by_name: Dict[str, MsgDecl] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                decl = _class_decl(node, relpath)
                if decl is not None:
                    by_name[decl.name] = decl
                    decls.append(decl)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                # module-level Cls.FIXED_FIELDS = [...]
                tgt = node.targets[0]
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr == "FIXED_FIELDS"
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in by_name):
                    fields = _literal_fields(node.value)
                    if fields is not None:
                        by_name[tgt.value.id].fixed_fields = fields
                        by_name[tgt.value.id].fixed_line = node.lineno
    return decls


def _class_decl(node: ast.ClassDef, relpath: str) -> Optional[MsgDecl]:
    type_id = version = None
    for deco in node.decorator_list:
        if (isinstance(deco, ast.Call)
                and ((isinstance(deco.func, ast.Name)
                      and deco.func.id == "message")
                     or (isinstance(deco.func, ast.Attribute)
                         and deco.func.attr == "message"))):
            if deco.args and isinstance(deco.args[0], ast.Constant):
                type_id = deco.args[0].value
            version = 1
            for kw in deco.keywords:
                if kw.arg == "version" and isinstance(kw.value, ast.Constant):
                    version = kw.value.value
    if type_id is None:
        return None
    decl = MsgDecl(name=node.name, file=relpath, line=node.lineno,
                   type_id=int(type_id), version=int(version or 1))
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            decl.fields.append((stmt.target.id, stmt.value is not None))
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            if stmt.targets[0].id == "FIXED_FIELDS":
                fields = _literal_fields(stmt.value)
                if fields is not None:
                    decl.fixed_fields = fields
                    decl.fixed_line = stmt.lineno
    return decl


def make_lock(decls: List[MsgDecl]) -> Dict:
    """The lockfile document for the current declarations."""
    return {
        "comment": "wire-ABI lockfile: update ONLY via "
                   "`python -m ceph_tpu.tools.lint --update-wire-lock` "
                   "after an append-only layout change + version bump",
        "messages": {
            d.name: {
                "id": d.type_id,
                "version": d.version,
                "fixed": ([list(f) for f in d.fixed_fields]
                          if d.fixed_fields is not None else None),
            }
            for d in sorted(decls, key=lambda d: d.type_id)
        },
    }


def load_lock(path: str) -> Optional[Dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def write_lock(path: str, decls: List[MsgDecl]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(make_lock(decls), fh, indent=1, sort_keys=True)
        fh.write("\n")


def check(root: str, lock_path: str,
          sources: Optional[List[Tuple[str, str]]] = None,
          corpus_dir: Optional[str] = None,
          coverage: bool = True) -> List[Finding]:
    if sources is None:
        sources = []
        for rel in WIRE_SOURCES:
            p = os.path.join(root, rel)
            if os.path.exists(p):
                with open(p, encoding="utf-8") as fh:
                    sources.append((rel, fh.read()))
    decls = extract(sources)
    findings = _check_decls(decls, load_lock(lock_path), lock_path, root)
    if coverage:
        findings += _check_coverage(corpus_dir)
    return findings


def _check_decls(decls: List[MsgDecl], lock: Optional[Dict],
                 lock_path: str, root: str) -> List[Finding]:
    findings: List[Finding] = []
    lock_rel = os.path.relpath(lock_path, root)

    by_id: Dict[int, MsgDecl] = {}
    by_name: Dict[str, MsgDecl] = {}
    for d in decls:
        if d.type_id in by_id:
            findings.append(Finding(
                check="wire-abi/duplicate-id", file=d.file, line=d.line,
                key=d.name,
                message=f"wire type id {d.type_id} of {d.name} already "
                        f"taken by {by_id[d.type_id].name} "
                        f"({by_id[d.type_id].file}:{by_id[d.type_id].line})"))
        else:
            by_id[d.type_id] = d
        by_name[d.name] = d

    if lock is None:
        findings.append(Finding(
            check="wire-abi/no-lockfile", file=lock_rel, line=1,
            key="ABI.lock",
            message=f"wire-ABI lockfile missing at {lock_rel}; run "
                    f"`python -m ceph_tpu.tools.lint --update-wire-lock` "
                    f"and commit it"))
        return findings

    locked = lock.get("messages", {})
    for name, rec in locked.items():
        d = by_name.get(name)
        if d is None:
            findings.append(Finding(
                check="wire-abi/removed", file=lock_rel, line=1, key=name,
                message=f"message {name} (wire id {rec['id']}) is in the "
                        f"lockfile but no longer declared — wire messages "
                        f"cannot be removed while peers may still send "
                        f"them (deprecate in place)"))
            continue
        if d.type_id != rec["id"]:
            findings.append(Finding(
                check="wire-abi/id-changed", file=d.file, line=d.line,
                key=name,
                message=f"{name} wire id changed {rec['id']} -> "
                        f"{d.type_id}; ids are forever (an old peer "
                        f"would decode the frame as the other type)"))
        if d.version < rec["version"]:
            findings.append(Finding(
                check="wire-abi/version-regressed", file=d.file,
                line=d.line, key=name,
                message=f"{name} version regressed v{rec['version']} -> "
                        f"v{d.version}"))
        findings += _check_layout(d, rec, name)

    for name, d in by_name.items():
        if name not in locked:
            findings.append(Finding(
                check="wire-abi/unlocked", file=d.file, line=d.line,
                key=name,
                message=f"message {name} (wire id {d.type_id}) is not in "
                        f"{lock_rel}; run --update-wire-lock and commit "
                        f"the lockfile diff alongside the new message"))
    return findings


def _check_layout(d: MsgDecl, rec: Dict, name: str) -> List[Finding]:
    findings: List[Finding] = []
    locked_fixed = rec.get("fixed")
    if locked_fixed is None and d.fixed_fields is None:
        return findings
    if locked_fixed is None:
        # pickled -> FIXED is a wire format change: old peers send pickle
        # frames the new FIXED decoder would misparse unless versioned
        if d.version <= rec["version"]:
            findings.append(Finding(
                check="wire-abi/tail-without-version-bump", file=d.file,
                line=d.fixed_line or d.line, key=name,
                message=f"{name} gained a FIXED layout without a version "
                        f"bump (locked v{rec['version']}, still "
                        f"v{d.version})"))
        return findings
    if d.fixed_fields is None:
        findings.append(Finding(
            check="wire-abi/layout-break", file=d.file, line=d.line,
            key=name,
            message=f"{name} lost its FIXED_FIELDS layout; the locked "
                    f"binary layout ({len(locked_fixed)} fields) is the "
                    f"wire contract"))
        return findings
    cur = [tuple(f) for f in d.fixed_fields]
    want = [tuple(f) for f in locked_fixed]
    for i, w in enumerate(want):
        if i >= len(cur):
            findings.append(Finding(
                check="wire-abi/layout-break", file=d.file,
                line=d.fixed_line or d.line, key=name,
                message=f"{name} FIXED_FIELDS truncated: locked field "
                        f"{i} {w} removed (layouts are append-only)"))
            return findings
        if cur[i] != w:
            findings.append(Finding(
                check="wire-abi/layout-break", file=d.file,
                line=d.fixed_line or d.line, key=name,
                message=f"{name} FIXED_FIELDS slot {i} changed "
                        f"{w} -> {cur[i]}: layouts are append-only "
                        f"(no insert/reorder/rename/retype; new fields "
                        f"go at the tail with a version bump)"))
            return findings
    if len(cur) > len(want) and d.version <= rec["version"]:
        findings.append(Finding(
            check="wire-abi/tail-without-version-bump", file=d.file,
            line=d.fixed_line or d.line, key=name,
            message=f"{name} FIXED_FIELDS grew "
                    f"{len(want)} -> {len(cur)} fields but the wire "
                    f"version did not bump (locked v{rec['version']}, "
                    f"still v{d.version}); old decoders need the "
                    f"version to know the tail may be truncated"))
    return findings


def _check_coverage(corpus_dir: Optional[str]) -> List[Finding]:
    """FIXED-type corpus/dencoder/golden coverage, via wire_corpus (one
    implementation of the walk, shared with ``wire_corpus --strict``)."""
    from ceph_tpu.tools import wire_corpus

    findings = []
    for gap in wire_corpus.coverage_gaps(corpus_dir or
                                         wire_corpus.CORPUS_DIR):
        findings.append(Finding(
            check="wire-abi/coverage", file=gap.file, line=gap.line,
            key=f"{gap.type_name}:{gap.kind}", message=gap.message))
    return findings
