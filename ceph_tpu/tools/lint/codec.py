"""codec hygiene checker family.

Binary codecs fail at the byte level, long after the typo: a struct
format string with one conversion too few packs garbage lengths; a
FIXED_FIELDS entry naming a field the dataclass doesn't declare (or one
without a default) breaks the truncated-tail decode rule the whole
golden-frame compatibility story rests on.  Statically checkable, so
check it statically:

- ``struct-arity``: ``struct.pack(fmt, ...)`` / ``pack_into`` with a
  constant format must receive exactly as many values as the format has
  conversions; module/class-level ``NAME = struct.Struct(fmt)``
  instances are tracked so ``NAME.pack(...)`` is checked too (starred
  args or dynamic formats are skipped, not guessed);
- ``fixed-field``: every FIXED_FIELDS entry must name a declared
  dataclass field, use a known kind code, and the field must carry a
  default — ``_unpack_fixed`` materializes truncated tails from the
  dataclass defaults, so a default-less field would make every old
  frame undecodable;
- ``fixed-tail-default``: post-v1 FIXED messages must keep ALL fields
  defaulted (the truncated-tail rule instantiates ``cls()``);
- ``slab-host-roundtrip``: a name bound from a slab gather
  (``*.gather_rows(...)`` / ``slab_gather(...)``) may be a DEVICE array
  on the pagestore's device arm; materializing it on the host
  (``np.asarray`` / ``np.frombuffer`` / ``.copy()``) outside the
  module's declared ``SLAB_IO_BOUNDARY`` helpers silently reintroduces
  the per-read d2h the device arm exists to delete — declare the exit
  or stay on device;
- unparsable files are reported here (one family owns the syntax check).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ceph_tpu.tools.lint.findings import Finding
from ceph_tpu.tools.lint.wire_abi import VALID_KINDS, extract

_FMT_TOKEN = re.compile(r"(\d*)([xcbB?hHiIlLqQnNefdspP])")


def _fmt_arity(fmt: str) -> Optional[int]:
    """Number of values a struct format consumes, or None if malformed."""
    body = fmt
    if body[:1] in "@=<>!":
        body = body[1:]
    pos, count = 0, 0
    for m in _FMT_TOKEN.finditer(body):
        if m.start() != pos:
            return None
        pos = m.end()
        rep = int(m.group(1)) if m.group(1) else 1
        conv = m.group(2)
        if conv == "x":
            continue  # pad byte: consumes no value
        if conv in "sp":
            count += 1  # N-byte string is ONE value
        else:
            count += rep
    if pos != len(body):
        return None
    return count


class _StructScanner(ast.NodeVisitor):
    def __init__(self, relpath: str, findings: List[Finding]):
        self.relpath = relpath
        self.findings = findings
        self.struct_vars: Dict[str, int] = {}  # NAME -> arity

    def visit_Assign(self, node):
        # NAME = struct.Struct("<fmt")  (module or class scope both walk
        # through here; instance attrs self.X are tracked by attr name)
        if len(node.targets) == 1 and isinstance(node.value, ast.Call):
            call = node.value
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "Struct" and call.args
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)):
                arity = _fmt_arity(call.args[0].value)
                tgt = node.targets[0]
                name = tgt.id if isinstance(tgt, ast.Name) else (
                    tgt.attr if isinstance(tgt, ast.Attribute) else None)
                if name is not None and arity is not None:
                    self.struct_vars[name] = arity
        self.generic_visit(node)

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "pack":
                self._check_pack(node, func, skip=0)
            elif func.attr == "pack_into":
                self._check_pack(node, func, skip=2)
        self.generic_visit(node)

    def _check_pack(self, node, func: ast.Attribute, skip: int) -> None:
        if any(isinstance(a, ast.Starred) for a in node.args) \
                or node.keywords:
            return  # dynamic arity: not checkable
        recv = func.value
        arity: Optional[int] = None
        fmt_src = ""
        args = node.args
        if isinstance(recv, ast.Name) and recv.id == "struct" \
                or (isinstance(recv, ast.Attribute)
                    and recv.attr == "struct"):
            # struct.pack(fmt, *vals) / struct.pack_into(fmt, buf, off, *v)
            if not args or not isinstance(args[0], ast.Constant) \
                    or not isinstance(args[0].value, str):
                return
            fmt_src = args[0].value
            arity = _fmt_arity(fmt_src)
            args = args[1:]
        else:
            # STRUCT_VAR.pack(*vals) / X.pack_into(buf, off, *vals)
            name = recv.id if isinstance(recv, ast.Name) else (
                recv.attr if isinstance(recv, ast.Attribute) else None)
            if name is None or name not in self.struct_vars:
                return
            arity = self.struct_vars[name]
            fmt_src = name
        if arity is None:
            return
        nvals = len(args) - skip
        if nvals != arity:
            self.findings.append(Finding(
                check="codec/struct-arity", file=self.relpath,
                line=node.lineno, key=f"{fmt_src}@L{node.lineno}",
                message=f"struct pack of {fmt_src!r} consumes {arity} "
                        f"value(s) but {nvals} given — mispacked lengths "
                        f"corrupt every frame downstream"))


_SLAB_GATHER_ATTRS = {"gather_rows"}
_SLAB_GATHER_NAMES = {"slab_gather"}
_HOST_MATERIALIZERS = {"asarray", "frombuffer"}


def _slab_boundary(tree: ast.Module) -> set:
    """Module-level ``SLAB_IO_BOUNDARY = ("fn", ...)`` — the declared
    host-exit helpers this module is allowed to materialize slab-gather
    results in."""
    names: set = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SLAB_IO_BOUNDARY" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    names.add(elt.value)
    return names


def _is_gather_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _SLAB_GATHER_ATTRS:
        return True
    return isinstance(f, ast.Name) and f.id in _SLAB_GATHER_NAMES


class _SlabScanner(ast.NodeVisitor):
    """codec/slab-host-roundtrip (see module docstring).  Purely
    name-local: a gather result is tracked per enclosing function, and
    only the three materializer shapes the device arm actually pays for
    are flagged — no alias chasing, no cross-function flow."""

    def __init__(self, relpath: str, boundary: set,
                 findings: List[Finding]):
        self.relpath = relpath
        self.boundary = boundary
        self.findings = findings
        self._fn: List[str] = []
        self._tainted: List[set] = []

    def _visit_fn(self, node):
        self._fn.append(node.name)
        self._tainted.append(set())
        self.generic_visit(node)
        self._fn.pop()
        self._tainted.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _flag(self, node, what: str) -> None:
        if self._fn and self._fn[-1] in self.boundary:
            return
        self.findings.append(Finding(
            check="codec/slab-host-roundtrip", file=self.relpath,
            line=node.lineno,
            key=f"{self._fn[-1] if self._fn else '<module>'}"
                f"@L{node.lineno}",
            message=f"{what} on a slab-gather result outside the "
                    f"declared SLAB_IO_BOUNDARY helpers — on the "
                    f"device arm this is a hidden per-read d2h; keep "
                    f"the result on device or declare the exit"))

    def visit_Assign(self, node):
        if self._tainted and _is_gather_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._tainted[-1].add(tgt.id)
        self.generic_visit(node)

    def _arg_tainted(self, arg) -> bool:
        if isinstance(arg, ast.Name) and self._tainted \
                and arg.id in self._tainted[-1]:
            return True
        return _is_gather_call(arg)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            if f.attr in _HOST_MATERIALIZERS \
                    and isinstance(recv, ast.Name) \
                    and recv.id in ("np", "numpy") and node.args \
                    and self._arg_tainted(node.args[0]):
                self._flag(node, f"np.{f.attr}")
            elif f.attr == "copy" and not node.args \
                    and self._arg_tainted(recv):
                self._flag(node, ".copy()")
        self.generic_visit(node)


def check(sources: List[Tuple[str, str]],
          wire_sources: Optional[List[Tuple[str, str]]] = None
          ) -> List[Finding]:
    findings: List[Finding] = []
    parsed: List[Tuple[str, str]] = []
    for relpath, text in sources:
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            findings.append(Finding(
                check="codec/syntax", file=relpath,
                line=e.lineno or 1, key="syntax",
                message=f"file does not parse: {e.msg}"))
            continue
        parsed.append((relpath, text))
        _StructScanner(relpath, findings).visit(tree)
        _SlabScanner(relpath, _slab_boundary(tree), findings).visit(tree)

    # FIXED layout hygiene over the wire-declaring modules (or the
    # doctored override a test feeds in)
    wire_srcs = wire_sources if wire_sources is not None else [
        (p, t) for p, t in parsed
        if p.endswith(("rados/types.py", "rados/messenger.py",
                       "mgr/daemon.py"))]
    for d in extract(wire_srcs):
        if d.fixed_fields is None:
            continue
        declared = {n for n, _ in d.fields}
        defaulted = {n for n, has in d.fields if has}
        for fname, kind in d.fixed_fields:
            if kind not in VALID_KINDS:
                findings.append(Finding(
                    check="codec/fixed-field", file=d.file,
                    line=d.fixed_line or d.line,
                    key=f"{d.name}.{fname}:kind",
                    message=f"{d.name}.FIXED_FIELDS: unknown kind "
                            f"{kind!r} for field {fname!r} (valid: "
                            f"{sorted(VALID_KINDS)})"))
            if fname not in declared:
                findings.append(Finding(
                    check="codec/fixed-field", file=d.file,
                    line=d.fixed_line or d.line,
                    key=f"{d.name}.{fname}:undeclared",
                    message=f"{d.name}.FIXED_FIELDS names {fname!r} "
                            f"but the dataclass declares no such field "
                            f"— decode would stamp a ghost attribute"))
            elif fname not in defaulted:
                findings.append(Finding(
                    check="codec/fixed-field", file=d.file,
                    line=d.fixed_line or d.line,
                    key=f"{d.name}.{fname}:no-default",
                    message=f"{d.name}.{fname} has no default: the "
                            f"truncated-tail decode rule materializes "
                            f"old frames from dataclass defaults, so "
                            f"every FIXED field needs one"))
        if d.version >= 2:
            for fname, has_default in d.fields:
                if not has_default:
                    findings.append(Finding(
                        check="codec/fixed-tail-default", file=d.file,
                        line=d.line, key=f"{d.name}.{fname}",
                        message=f"{d.name} is v{d.version} but field "
                                f"{fname!r} has no default — "
                                f"`_unpack_fixed` instantiates `cls()` "
                                f"to default unsent tails, which "
                                f"requires every field defaulted"))
    return findings
