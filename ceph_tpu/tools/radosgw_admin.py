"""radosgw-admin CLI: user/quota/usage administration for a gateway's
pool (the reference's src/rgw/rgw_admin.cc minimal surface).

    python -m ceph_tpu.tools.radosgw_admin --mon HOST:PORT --pool rgw \\
        user create --uid alice --display-name "Alice"
    ... user list | user info --uid alice | user rm --uid alice
    ... user suspend --uid alice | user enable --uid alice
    ... quota set --uid alice --scope user --max-size 1048576
    ... quota enable --uid alice --scope user
    ... usage --uid alice

Prints one JSON document per command (machine-parseable, like the
reference's --format=json)."""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="rgw admin tool")
    p.add_argument("--mon", required=True, help="mon address host:port")
    p.add_argument("--pool", required=True, help="gateway pool name")
    sub = p.add_subparsers(dest="cmd", required=True)

    user = sub.add_parser("user")
    usub = user.add_subparsers(dest="action", required=True)
    for action in ("create", "rm", "info", "suspend", "enable"):
        sp = usub.add_parser(action)
        sp.add_argument("--uid", required=True)
        if action == "create":
            sp.add_argument("--display-name", default="")
            sp.add_argument("--access-key")
            sp.add_argument("--secret-key")
    usub.add_parser("list")

    quota = sub.add_parser("quota")
    qsub = quota.add_subparsers(dest="action", required=True)
    for action in ("set", "enable", "disable"):
        sp = qsub.add_parser(action)
        sp.add_argument("--uid", required=True)
        sp.add_argument("--scope", choices=("user", "bucket"),
                        default="user")
        if action == "set":
            sp.add_argument("--max-size", type=int, default=-1)
            sp.add_argument("--max-objects", type=int, default=-1)

    usage = sub.add_parser("usage")
    usage.add_argument("--uid", required=True)

    return p.parse_args(argv)


async def run(args) -> int:
    from ceph_tpu.rados.librados import Rados
    from ceph_tpu.services.rgw import RgwAdmin, RgwService

    host, port = args.mon.rsplit(":", 1)
    rados = await Rados((host, int(port))).connect()
    try:
        ioctx = await rados.open_ioctx(args.pool)
        admin = RgwAdmin(RgwService(ioctx))
        if args.cmd == "user":
            if args.action == "create":
                out = await admin.user_create(
                    args.uid, args.display_name,
                    access_key=args.access_key,
                    secret_key=args.secret_key)
            elif args.action == "rm":
                await admin.user_rm(args.uid)
                out = {"removed": args.uid}
            elif args.action == "info":
                out = await admin.user_info(args.uid)
            elif args.action == "suspend":
                await admin.user_suspend(args.uid)
                out = {"uid": args.uid, "suspended": True}
            elif args.action == "enable":
                await admin.user_enable(args.uid)
                out = {"uid": args.uid, "suspended": False}
            else:
                out = await admin.user_list()
        elif args.cmd == "quota":
            if args.action == "set":
                await admin.quota_set(args.uid, args.scope,
                                      args.max_size, args.max_objects)
            elif args.action == "enable":
                await admin.quota_enable(args.uid, args.scope)
            else:
                await admin.quota_disable(args.uid, args.scope)
            out = (await admin.user_info(args.uid)).get(
                "quota" if args.scope == "user" else "bucket_quota")
        else:
            out = await admin.usage(args.uid)
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    finally:
        await rados.shutdown()


def main(argv=None) -> int:
    try:
        return asyncio.run(run(parse_args(argv)))
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
