"""Benchmark sweep: plugins x techniques x (k, m), the qa bench.sh
equivalent (reference qa/workunits/erasure-code/bench.sh:52-174).

Emits one JSON line per cell:
  {"plugin":..., "technique":..., "k":..., "m":..., "workload":...,
   "seconds":..., "kb":..., "mbps":...}

    python -m ceph_tpu.tools.bench_suite --size 1048576 --iterations 4
"""

from __future__ import annotations

import argparse
import io
import json
import sys
from contextlib import redirect_stdout

# bench.sh's k -> [m...] map (bench.sh:52-56)
K2MS = {2: [1, 2], 3: [2, 3], 4: [2, 3], 6: [2, 3, 4], 10: [3, 4]}


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="erasure code benchmark sweep")
    p.add_argument("--size", type=int, default=1024 * 1024)
    p.add_argument("--iterations", type=int, default=4)
    p.add_argument("--plugins", default="jerasure,isa,tpu",
                   help="comma list of plugins to sweep")
    p.add_argument("--workloads", default="encode,decode")
    p.add_argument("--ks", default=",".join(str(k) for k in K2MS))
    return p.parse_args(argv)


TECHNIQUES = {
    "jerasure": ["reed_sol_van", "cauchy_good"],
    "isa": ["reed_sol_van", "cauchy"],
    "tpu": ["reed_sol_van", "cauchy_good"],
}


def main(argv=None) -> int:
    from ceph_tpu.tools import benchmark

    args = parse_args(argv)
    failures = 0
    for plugin in args.plugins.split(","):
        for technique in TECHNIQUES.get(plugin, ["reed_sol_van"]):
            for k in (int(x) for x in args.ks.split(",")):
                for m in K2MS.get(k, [2]):
                    for workload in args.workloads.split(","):
                        argv_b = [
                            "--plugin", plugin, "--workload", workload,
                            "--size", str(args.size),
                            "--iterations", str(args.iterations),
                            "-P", f"k={k}", "-P", f"m={m}",
                            "-P", f"technique={technique}",
                        ]
                        buf = io.StringIO()
                        try:
                            with redirect_stdout(buf):
                                code = benchmark.main(argv_b)
                        except Exception as e:
                            print(f"# {plugin}/{technique} k={k} m={m} "
                                  f"{workload}: {e}", file=sys.stderr)
                            failures += 1
                            continue
                        if code:
                            failures += 1
                            continue
                        seconds_s, kb_s = buf.getvalue().strip().split("\t")
                        seconds, kb = float(seconds_s), int(kb_s)
                        print(json.dumps({
                            "plugin": plugin, "technique": technique,
                            "k": k, "m": m, "workload": workload,
                            "seconds": seconds, "kb": kb,
                            "mbps": (kb / 1024) / seconds if seconds else None,
                        }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
