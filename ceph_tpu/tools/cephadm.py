"""cephadm-lite: the deploy/orchestration plane (reference src/cephadm/).

The reference's cephadm bootstraps and manages cluster daemons as
supervised containers; the role here is the same life-cycle surface over
real OS processes — each cluster is a detached daemon-host process
(``python -m ceph_tpu.rados.vstart``) with durable stores under its data
directory, registered in a spec file the other subcommands read:

    python -m ceph_tpu.tools.cephadm bootstrap --name c1 --osds 3 \
        --data-root /tmp/clusters
    python -m ceph_tpu.tools.cephadm ls --data-root /tmp/clusters
    python -m ceph_tpu.tools.cephadm stop --name c1 --data-root ...
    python -m ceph_tpu.tools.cephadm rm-cluster --name c1 --data-root ...

``bootstrap`` waits for the daemon host to publish its mon quorum (the
addr file), then records {name, pid, mons, osds, data} — the registry
``ls`` reports with per-cluster liveness (pid probe), like ``cephadm ls``
reports daemon state.  ``rm-cluster`` stops the daemons and deletes the
cluster's data, the reference's destructive teardown (guarded by the same
--force acknowledgement).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


def _spec_path(root: str, name: str) -> str:
    return os.path.join(root, name, "cluster.json")


def _load_spec(root: str, name: str) -> Optional[Dict]:
    try:
        with open(_spec_path(root, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        # reap if it is OUR child (the CLI that bootstrapped may still be
        # the parent): a zombie answers kill(pid, 0) but is not alive
        os.waitpid(pid, os.WNOHANG)
    except (ChildProcessError, PermissionError):
        pass
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            if f.read().split(")")[-1].split()[0] == "Z":
                return False  # zombie: dead, awaiting reap elsewhere
    except OSError:
        pass
    return True


def bootstrap(args) -> int:
    cdir = os.path.join(args.data_root, args.name)
    if _load_spec(args.data_root, args.name) is not None:
        print(f"cluster {args.name!r} already exists", file=sys.stderr)
        return 1
    os.makedirs(cdir, exist_ok=True)
    addr_file = os.path.join(cdir, "mons.json")
    try:
        os.unlink(addr_file)  # a stale file from a failed bootstrap
    except FileNotFoundError:
        pass
    log_path = os.path.join(cdir, "daemon.log")
    cmd = [sys.executable, "-m", "ceph_tpu.rados.vstart",
           "--osds", str(args.osds), "--mons", str(args.mons),
           "--data-dir", os.path.join(cdir, "data"),
           "--addr-file", addr_file,
           "--control-file", os.path.join(cdir, "orch_spec.json")]
    if args.mgr:
        cmd.append("--mgr")
    # scrubbed accelerator env: on hosts whose sitecustomize force-
    # registers a TPU plugin, JAX_PLATFORMS=cpu alone is NOT honored and
    # the detached daemon would collide with an accelerator-holding
    # process on the libtpu lockfile
    from ceph_tpu.utils.jaxdev import scrub_accelerator_env

    env = scrub_accelerator_env()
    # detached daemon host (start_new_session: survives this CLI's exit,
    # the reference's systemd-unit role in miniature)
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                start_new_session=True, env=env,
                                cwd=os.path.dirname(os.path.dirname(
                                    os.path.dirname(
                                        os.path.abspath(__file__)))))
    deadline = time.monotonic() + args.timeout
    info = None
    while time.monotonic() < deadline:
        try:
            with open(addr_file) as f:
                info = json.load(f)
            break
        except (OSError, ValueError):
            if proc.poll() is not None:
                print(f"daemon host exited rc={proc.returncode}; "
                      f"see {log_path}", file=sys.stderr)
                return 1
            time.sleep(0.2)
    if info is None:
        # the clean-shutdown path (SIGINT -> cluster.stop()), with the
        # same kill fallback and a reap so no zombie outlives the CLI
        try:
            proc.send_signal(signal.SIGINT)
            proc.wait(timeout=10)
        except (subprocess.TimeoutExpired, ProcessLookupError):
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        print(f"bootstrap timed out after {args.timeout}s", file=sys.stderr)
        return 1
    spec = {"name": args.name, "pid": proc.pid,
            "mons": info["mons"], "osds": args.osds,
            "data": cdir, "created": time.time()}
    with open(_spec_path(args.data_root, args.name), "w") as f:
        json.dump(spec, f)
    mon = info["mons"][0]
    print(f"cluster {args.name!r} up: mon {mon[0]}:{mon[1]}, "
          f"{args.osds} osds (pid {proc.pid})")
    print(f"  ceph: python -m ceph_tpu.tools.ceph --mon "
          f"{mon[0]}:{mon[1]} status")
    return 0


def ls(args) -> int:
    rows: List[Dict] = []
    if os.path.isdir(args.data_root):
        for name in sorted(os.listdir(args.data_root)):
            spec = _load_spec(args.data_root, name)
            if spec is None:
                continue
            spec["state"] = ("running" if _alive(spec.get("pid", -1))
                             else "stopped")
            rows.append(spec)
    if args.format == "json":
        print(json.dumps(rows))
    else:
        for s in rows:
            mon = s["mons"][0] if s.get("mons") else ("?", 0)
            print(f"{s['name']:<16} {s['state']:<8} pid {s['pid']:<8} "
                  f"mon {mon[0]}:{mon[1]} osds {s['osds']}")
    return 0


def _stop_daemons(spec: Dict, grace: float = 10.0) -> None:
    pid = spec.get("pid", -1)
    if pid > 0 and _alive(pid):
        os.kill(pid, signal.SIGINT)  # vstart's clean-shutdown path
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and _alive(pid):
            time.sleep(0.1)
        if _alive(pid):
            os.kill(pid, signal.SIGKILL)


def stop(args) -> int:
    spec = _load_spec(args.data_root, args.name)
    if spec is None:
        print(f"no cluster {args.name!r}", file=sys.stderr)
        return 1
    _stop_daemons(spec)
    spec["pid"] = -1
    with open(_spec_path(args.data_root, args.name), "w") as f:
        json.dump(spec, f)
    print(f"cluster {args.name!r} stopped (data retained)")
    return 0


def rm_cluster(args) -> int:
    spec = _load_spec(args.data_root, args.name)
    if spec is None:
        print(f"no cluster {args.name!r}", file=sys.stderr)
        return 1
    if not args.force:
        print("rm-cluster deletes the cluster's DATA; re-run with "
              "--force to confirm", file=sys.stderr)
        return 1
    _stop_daemons(spec)
    shutil.rmtree(os.path.join(args.data_root, args.name),
                  ignore_errors=True)
    print(f"cluster {args.name!r} removed")
    return 0


def orch_apply(args) -> int:
    """`ceph orch apply osd` role: write the service spec; the daemon
    host's reconciliation loop converges the live daemon set to it."""
    if args.osds < 1:
        # the reconcile loop never drains below one OSD (a clusterless
        # cluster is rm-cluster's job) — reject rather than publish a
        # spec that can never converge
        print("--osds must be >= 1", file=sys.stderr)
        return 1
    spec = _load_spec(args.data_root, args.name)
    if spec is None:
        print(f"no cluster {args.name!r}", file=sys.stderr)
        return 1
    cdir = os.path.join(args.data_root, args.name)
    control = os.path.join(cdir, "orch_spec.json")
    tmp = control + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"target_osds": args.osds}, f)
    os.replace(tmp, control)
    print(f"scheduled: {args.name} -> {args.osds} osds "
          f"(daemon host converges within its poll interval)")
    return 0


def orch_ps(args) -> int:
    """`ceph orch ps` role: live per-daemon table — registry liveness
    for the host process plus the mon's osd up/in states."""
    spec = _load_spec(args.data_root, args.name)
    if spec is None:
        print(f"no cluster {args.name!r}", file=sys.stderr)
        return 1
    # re-read the addr file: reconciliation republishes osd counts
    addr_file = os.path.join(args.data_root, args.name, "mons.json")
    try:
        with open(addr_file) as f:
            info = json.load(f)
    except (OSError, ValueError):
        info = {"mons": spec["mons"], "osds": spec["osds"]}
    rows: List[Dict] = [{"daemon": "host", "id": spec["name"],
                         "status": "running" if _alive(spec["pid"])
                         else "stopped", "pid": spec["pid"]}]
    import asyncio as _asyncio

    async def probe():
        from ceph_tpu.rados.client import RadosClient

        mon = info["mons"][0]
        c = RadosClient((mon[0], int(mon[1])))
        await c.start()
        try:
            await c.refresh_map()
            for osd_id in sorted(c.osdmap.osds):
                st = c.osdmap.osds[osd_id]
                rows.append({
                    "daemon": "osd", "id": osd_id,
                    "status": "running" if st.up else "stopped",
                    "addr": f"{st.addr[0]}:{st.addr[1]}" if st.addr
                    else ""})
            for rank, mon_addr in enumerate(info["mons"]):
                rows.append({"daemon": "mon", "id": rank,
                             "status": "running",
                             "addr": f"{mon_addr[0]}:{mon_addr[1]}"})
        finally:
            await c.stop()

    try:
        _asyncio.run(probe())
    except Exception as e:
        rows.append({"daemon": "mon", "id": "?",
                     "status": f"unreachable ({type(e).__name__})"})
    if args.format == "json":
        print(json.dumps(rows, indent=2))
    else:
        for r in rows:
            print(f"{r['daemon']:>5}.{r['id']:<8} {r['status']:<10} "
                  f"{r.get('addr', '')}")
    return 0


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="cluster deploy tool")
    p.add_argument("--data-root", default="./ceph-clusters",
                   help="registry directory holding one subdir per cluster")
    sub = p.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("bootstrap")
    b.add_argument("--name", required=True)
    b.add_argument("--osds", type=int, default=3)
    b.add_argument("--mons", type=int, default=1)
    b.add_argument("--mgr", action="store_true")
    b.add_argument("--timeout", type=float, default=120.0)

    l = sub.add_parser("ls")
    l.add_argument("--format", choices=("plain", "json"), default="plain")

    s = sub.add_parser("stop")
    s.add_argument("--name", required=True)

    r = sub.add_parser("rm-cluster")
    r.add_argument("--name", required=True)
    r.add_argument("--force", action="store_true")

    oa = sub.add_parser("orch-apply",
                        help="converge a cluster's OSD count to a spec")
    oa.add_argument("--name", required=True)
    oa.add_argument("--osds", type=int, required=True)

    op = sub.add_parser("orch-ps",
                        help="live per-daemon status table")
    op.add_argument("--name", required=True)
    op.add_argument("--format", choices=("plain", "json"),
                    default="plain")

    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    return {"bootstrap": bootstrap, "ls": ls, "stop": stop,
            "rm-cluster": rm_cluster, "orch-apply": orch_apply,
            "orch-ps": orch_ps}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
