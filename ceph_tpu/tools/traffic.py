"""Macro traffic harness: thousands of simulated tenants, zipfian object
popularity, mixed op phases.

The workload shape of a production EC cluster — "Understanding System
Characteristics of Online Erasure Coding on SSD Array Systems"
(PAPERS.md, arXiv:1709.05365) characterizes the mix this harness
reproduces: write-heavy ingest, read-heavy serving with a skewed
(zipfian) popularity curve, degraded reads under a downed OSD, and
client traffic concurrent with repair.  bench.py --macro and
tools/non_regression.py --qos drive it against an in-process cluster;
the per-tenant-class latency records it produces land in the BENCH
record next to wire_perf/objecter_perf/tier_perf.

Shape: thousands of simulated TENANTS ride a handful of client
PROCESSES (RadosClient instances) — each op is stamped with its tenant's
entity name (``client.<class>.<id>``, the MOSDOp v6 ``client`` field),
so the OSD's per-client dmClock QoS sees thousands of identities through
a few connections, exactly the production multiplexing shape.  Each
tenant class gets its OWN client process: an MOSDBackoff aimed at a
flooding class parks that class's connection, never its neighbors'.

Latency accounting is end-to-end client-side per (tenant class, op
kind), reduced by the same nearest-rank percentile_summary the optracker
path uses; OSD-side per-phase per-class percentiles come from the
tracker's ``cls:<name>|<phase>`` sample rings (tracked_op.py) and are
merged by the caller.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ceph_tpu.common.tracked_op import percentile_summary


@dataclass
class TenantClass:
    """One declared tenant class driving load through one client
    process.

    ``tenants`` simulated identities share the class's QoS profile
    (pool opt ``qos_class:<name>``); ``workers`` concurrent op loops
    model the class's parallelism; ``rate`` > 0 paces the class's
    offered load to that many ops/sec total (0 = flat out — the
    flooding shape)."""

    name: str  # tenant class ("" = the pool's default client profile)
    client: object  # RadosClient carrying this class's connections
    tenants: int = 100
    workers: int = 4
    rate: float = 0.0  # offered ops/sec (0 = unpaced)
    write_frac: Optional[float] = None  # override the phase's mix


@dataclass
class PhaseStats:
    """Per-(class, op-kind) latency samples + failure counts for one
    phase run."""

    name: str
    seconds: float = 0.0
    samples: Dict[str, Dict[str, List[float]]] = field(default_factory=dict)
    failures: Dict[str, int] = field(default_factory=dict)
    ops: Dict[str, int] = field(default_factory=dict)

    def record(self, cls: str, kind: str, dt: float, ok: bool) -> None:
        key = cls or "default"
        self.ops[key] = self.ops.get(key, 0) + 1
        if ok:
            self.samples.setdefault(key, {}).setdefault(kind, []).append(dt)
        else:
            self.failures[key] = self.failures.get(key, 0) + 1

    def summary(self) -> Dict[str, Dict]:
        """{class: {op: {p50_us,p99_us,p999_us,count}, ops, failures,
        ops_per_sec}} — the per-tenant-class shape the BENCH record
        embeds."""
        out: Dict[str, Dict] = {}
        for cls in sorted(set(self.ops) | set(self.samples)):
            kinds = self.samples.get(cls, {})
            out[cls] = {k: percentile_summary(v) for k, v in kinds.items()}
            out[cls]["ops"] = self.ops.get(cls, 0)
            out[cls]["failures"] = self.failures.get(cls, 0)
            if self.seconds > 0:
                out[cls]["ops_per_sec"] = round(
                    self.ops.get(cls, 0) / self.seconds, 1)
        return out


def zipf_weights(n: int, s: float = 1.1) -> np.ndarray:
    """Rank-weighted zipfian popularity over n objects (rank r gets
    1/(r+1)^s), normalized."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


class TrafficHarness:
    """Drive mixed-phase multi-tenant traffic at one pool.

    The object namespace is shared (``o0..o<n>``) with zipfian
    popularity — the skew that makes a handful of objects carry most of
    the read load.  ``preload()`` writes every object once so reads
    always resolve; writes rewrite an object's deterministic content, so
    any read can verify byte-identity against the expected blob
    (``verify=True``)."""

    def __init__(self, classes: Sequence[TenantClass], pool_id: int,
                 n_objects: int = 48, obj_size: int = 32 << 10,
                 zipf_s: float = 1.1, seed: int = 0,
                 verify: bool = False):
        self.classes = list(classes)
        self.pool_id = pool_id
        self.n_objects = int(n_objects)
        self.obj_size = int(obj_size)
        self.verify = verify
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._weights = zipf_weights(self.n_objects, zipf_s)
        blob_rng = np.random.default_rng(seed + 1)
        self.blobs = {
            f"o{i}": blob_rng.integers(
                0, 256, self.obj_size, dtype=np.uint8).tobytes()
            for i in range(self.n_objects)}
        # tenant identity pool per class: client.<class>.<i> (or the
        # plain client.t<i> default-profile shape for the "" class)
        self.tenant_names: Dict[str, List[str]] = {}
        for tc in self.classes:
            self.tenant_names[tc.name] = [
                f"client.{tc.name}.{i}" if tc.name else f"client.t{i}"
                for i in range(max(1, tc.tenants))]

    async def preload(self) -> None:
        """Write every object once (any client) so read phases resolve."""
        c = self.classes[0].client
        for oid, blob in self.blobs.items():
            await c.put(self.pool_id, oid, blob)

    def _pick_oid(self, rng: np.random.Generator) -> str:
        # draws ride the CALLER's generator: workers use their own
        # per-(class, worker) stream, so runs reproduce regardless of
        # task interleaving (the shared self._rng would not)
        return f"o{rng.choice(self.n_objects, p=self._weights)}"

    async def _worker(self, tc: TenantClass, write_frac: float,
                      deadline: float, stats: PhaseStats,
                      worker_idx: int) -> None:
        # deterministic per-(class, worker) stream: hash() is randomized
        # per process and would make runs irreproducible
        ci = self.classes.index(tc) if tc in self.classes else 0
        rng = np.random.default_rng(
            self.seed * 1_000_003 + ci * 1000 + worker_idx)
        names = self.tenant_names[tc.name]
        per_worker_rate = tc.rate / max(1, tc.workers) if tc.rate else 0.0
        next_t = time.monotonic()
        wf = tc.write_frac if tc.write_frac is not None else write_frac
        while time.monotonic() < deadline:
            if per_worker_rate:
                # paced class: hold the offered rate (sleep to the slot)
                next_t += 1.0 / per_worker_rate
                pause = next_t - time.monotonic()
                if pause > 0:
                    await asyncio.sleep(pause)
                    if time.monotonic() >= deadline:
                        return
            tenant = names[int(rng.integers(len(names)))]
            oid = self._pick_oid(rng)
            is_write = rng.random() < wf
            t0 = time.monotonic()
            ok = True
            try:
                if is_write:
                    await tc.client.put(self.pool_id, oid,
                                        self.blobs[oid], client=tenant)
                else:
                    got = await tc.client.get(self.pool_id, oid,
                                              client=tenant)
                    if self.verify and bytes(got) != self.blobs[oid]:
                        ok = False
            except Exception:
                ok = False
            stats.record(tc.name, "put" if is_write else "get",
                         time.monotonic() - t0, ok)

    async def run_phase(self, name: str, seconds: float,
                        write_frac: float,
                        classes: Optional[Sequence[TenantClass]] = None
                        ) -> PhaseStats:
        """One mixed phase: every class's workers drive ops until the
        deadline; returns the per-class latency/failure record.
        ``classes`` restricts the phase to a subset (the solo arm of the
        isolation experiment)."""
        stats = PhaseStats(name=name)
        deadline = time.monotonic() + seconds
        t0 = time.monotonic()
        tasks = []
        loop = asyncio.get_running_loop()
        for tc in (classes if classes is not None else self.classes):
            for w in range(max(1, tc.workers)):
                tasks.append(loop.create_task(
                    self._worker(tc, write_frac, deadline, stats, w)))
        await asyncio.gather(*tasks)
        stats.seconds = time.monotonic() - t0
        return stats


def merge_osd_class_phases(osds) -> Dict[str, Dict[str, Dict]]:
    """Reduce the OSDs' per-tenant-class optracker rings
    (``cls:<name>|<phase>`` keys, tracked_op.py) to
    {class: {phase: {p50_us,p99_us,p999_us,count}}} — the OSD-side half
    of the per-tenant-class BENCH record."""
    merged: Dict[str, Dict[str, List[float]]] = {}
    for o in osds:
        for key, samples in o.ctx.op_tracker.phase_samples().items():
            if not key.startswith("cls:"):
                continue
            cls, phase = key[4:].split("|", 1)
            merged.setdefault(cls, {}).setdefault(phase, []).extend(samples)
    return {cls: {ph: percentile_summary(ss) for ph, ss in phases.items()}
            for cls, phases in merged.items()}
