"""rbd CLI: image lifecycle, snapshots, and export/import/diff backup
workflows (reference src/tools/rbd minimal surface).

    python -m ceph_tpu.tools.rbd --mon HOST:PORT --pool p create img --size 64M
    ... ls | info img | resize img --size 128M | rm img
    ... snap create img@s1 | snap ls img
    ... export img ./img.full            # sparse-preserving full export
    ... import ./img.full img2
    ... export-diff img --from-snap s1 ./img.delta
    ... import-diff ./img.delta img2
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _parse_size(s: str) -> int:
    s = s.strip().upper()
    mult = 1
    for suffix, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30)):
        if s.endswith(suffix):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


def _split_at(spec: str):
    """img or img@snap -> (img, snap|None)."""
    name, _, snap = spec.partition("@")
    return name, (snap or None)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="rbd image tool")
    p.add_argument("--mon", required=True, help="mon address host:port")
    p.add_argument("--pool", required=True)
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("create")
    c.add_argument("image")
    c.add_argument("--size", required=True, help="e.g. 64M, 1G")
    c.add_argument("--order", type=int, default=22)

    sub.add_parser("ls")

    du = sub.add_parser("du", help="provisioned vs USED bytes per image "
                                   "(fast-diff object-map accounting)")
    du.add_argument("image", nargs="?", help="one image (default: all)")

    i = sub.add_parser("info")
    i.add_argument("image")

    r = sub.add_parser("resize")
    r.add_argument("image")
    r.add_argument("--size", required=True)

    d = sub.add_parser("rm")
    d.add_argument("image")

    sn = sub.add_parser("snap")
    sn.add_argument("action", choices=("create", "ls", "rm"))
    sn.add_argument("spec", help="img@snap (ls: img)")

    tr = sub.add_parser("trash")
    tr.add_argument("action", choices=("mv", "ls", "restore", "purge"))
    tr.add_argument("target", nargs="?", default=None,
                    help="mv: image name; restore: trash id")
    tr.add_argument("--delay", type=float, default=0.0,
                    help="mv: deferment seconds before purge may reclaim")
    tr.add_argument("--image", default=None,
                    help="restore: optional new image name")
    tr.add_argument("--force", action="store_true",
                    help="purge: ignore deferment windows")

    e = sub.add_parser("export")
    e.add_argument("spec", help="img or img@snap")
    e.add_argument("path")

    im = sub.add_parser("import")
    im.add_argument("path")
    im.add_argument("image")
    im.add_argument("--order", type=int, default=22)

    ed = sub.add_parser("export-diff")
    ed.add_argument("spec", help="img or img@snap (the TO side)")
    ed.add_argument("path")
    ed.add_argument("--from-snap", default=None)

    idf = sub.add_parser("import-diff")
    idf.add_argument("path")
    idf.add_argument("image")

    return p.parse_args(argv)


async def run(args) -> int:
    from ceph_tpu.rados.librados import Rados
    from ceph_tpu.services.rbd import RBD
    from ceph_tpu.services import rbd_export

    host, port = args.mon.rsplit(":", 1)
    rados = await Rados((host, int(port))).connect()
    try:
        ioctx = await rados.open_ioctx(args.pool)
        rbd = RBD(ioctx)
        if args.cmd == "create":
            await rbd.create(args.image, _parse_size(args.size),
                             order=args.order)
            print(f"created {args.image}")
        elif args.cmd == "ls":
            for name in await rbd.list():
                print(name)
        elif args.cmd == "du":
            # reference `rbd du`: USED = allocated blocks from the
            # object map (the fast-diff accounting), no data reads;
            # snapshots add their own pinned allocations
            names = [args.image] if args.image else await rbd.list()
            rows = []
            for name in names:
                img = await rbd.open(name)
                used = len(img._hdr.get("object_map", ())) \
                    * img.object_size
                snap_used = 0
                for info in img._snaps().values():
                    snap_used += len(info.get("object_map", ())) \
                        * img.object_size
                rows.append({"NAME": name, "PROVISIONED": img.size,
                             "USED": used, "SNAP_USED": snap_used})
            print(f"{'NAME':<20} {'PROVISIONED':>14} {'USED':>14} "
                  f"{'SNAP_USED':>14}")
            for r in rows:
                print(f"{r['NAME']:<20} {r['PROVISIONED']:>14} "
                      f"{r['USED']:>14} {r['SNAP_USED']:>14}")
            if not args.image:
                print(f"{'TOTAL':<20} "
                      f"{sum(r['PROVISIONED'] for r in rows):>14} "
                      f"{sum(r['USED'] for r in rows):>14} "
                      f"{sum(r['SNAP_USED'] for r in rows):>14}")
        elif args.cmd == "info":
            img = await rbd.open(args.image)
            print(json.dumps(await img.stat(), indent=2, sort_keys=True))
        elif args.cmd == "resize":
            img = await rbd.open(args.image)
            await img.resize(_parse_size(args.size))
            print(f"resized {args.image} to {args.size}")
        elif args.cmd == "rm":
            await rbd.remove(args.image)
            print(f"removed {args.image}")
        elif args.cmd == "snap":
            name, snap = _split_at(args.spec)
            img = await rbd.open(name)
            if args.action == "create":
                if not snap:
                    raise SystemExit("snap create needs img@snap")
                await img.snap_create(snap)
                print(f"created {args.spec}")
            elif args.action == "rm":
                if not snap:
                    raise SystemExit("snap rm needs img@snap")
                await img.snap_remove(snap)
                print(f"removed {args.spec}")
            else:
                for s in img.snap_list():
                    print(s)
        elif args.cmd == "trash":
            if args.action == "mv":
                if not args.target:
                    raise SystemExit("trash mv needs an image name")
                tid = await rbd.trash_mv(args.target, delay=args.delay)
                print(json.dumps({"id": tid}))
            elif args.action == "ls":
                print(json.dumps(await rbd.trash_ls(), indent=2))
            elif args.action == "restore":
                if not args.target:
                    raise SystemExit("trash restore needs a trash id")
                img = await rbd.trash_restore(args.target,
                                              new_name=args.image)
                print(f"restored {img.name}")
            else:
                n = await rbd.trash_purge(force=args.force)
                print(json.dumps({"purged": n}))
        elif args.cmd == "export":
            name, snap = _split_at(args.spec)
            img = await rbd.open(name)
            with open(args.path, "wb") as f:
                stats = await rbd_export.export_image(img, f, snap=snap)
            print(json.dumps(stats))
        elif args.cmd == "import":
            with open(args.path, "rb") as f:
                await rbd_export.import_image(rbd, args.image, f,
                                              order=args.order)
            print(f"imported {args.image}")
        elif args.cmd == "export-diff":
            name, snap = _split_at(args.spec)
            img = await rbd.open(name)
            with open(args.path, "wb") as f:
                stats = await rbd_export.export_diff(
                    img, f, from_snap=args.from_snap, to_snap=snap)
            print(json.dumps(stats))
        elif args.cmd == "import-diff":
            img = await rbd.open(args.image)
            with open(args.path, "rb") as f:
                stats = await rbd_export.apply_diff(img, f)
            print(json.dumps({"writes": stats["writes"],
                              "trims": stats["trims"]}))
        return 0
    finally:
        await rados.shutdown()


def main(argv=None) -> int:
    try:
        return asyncio.run(run(parse_args(argv)))
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
