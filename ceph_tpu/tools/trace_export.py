"""Export one stitched op trace as Jaeger-compatible JSON.

The per-daemon tracer rings each hold only their OWN spans of a
cross-daemon trace (client root, the primary's osd_op/ec write spans,
every sub-write peer's span).  This tool gathers `dump_trace <id>`
answers across daemons and emits the whole tree in Jaeger's JSON upload
shape (the `jaeger-ui` / `jaeger query` import format), so one EC write
renders as client -> primary -> k+m sub-write peers under a single
traceID.

    python -m ceph_tpu.tools.trace_export --asok-dir DIR --trace <hex>
    python -m ceph_tpu.tools.trace_export --asok-dir DIR --trace <hex> -o op.json

In-process callers (tests, bench) use ``collect_spans`` /
``to_jaeger`` directly with tracer objects or pre-dumped span lists.
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import sys
from typing import Any, Dict, Iterable, List


def collect_spans(sources: Iterable[Any], trace_id: str) -> List[Dict]:
    """Gather one trace's spans from a mix of sources: Tracer objects,
    span-dump lists, or {"spans": [...]} asok replies."""
    spans: List[Dict] = []
    seen = set()
    for src in sources:
        if hasattr(src, "spans_for"):
            got = src.spans_for(trace_id)
        elif isinstance(src, dict):
            got = src.get("spans", [])
        else:
            got = [d for d in src if d.get("trace_id") == trace_id]
        for d in got:
            key = d.get("span_id")
            if key in seen:
                continue
            seen.add(key)
            spans.append(d)
    return spans


def resolve_parents(spans: List[Dict]) -> Dict[str, int]:
    """{span_id -> child count}; spans whose parent_id names a span NOT
    in the set are orphans (a daemon's ring evicted the parent)."""
    ids = {d["span_id"] for d in spans}
    orphans = sum(1 for d in spans
                  if d.get("parent_id") and d["parent_id"] not in ids)
    children: Dict[str, int] = {}
    for d in spans:
        p = d.get("parent_id")
        if p:
            children[p] = children.get(p, 0) + 1
    children["__orphans__"] = orphans
    return children


def to_jaeger(trace_id: str, spans: List[Dict]) -> Dict:
    """Jaeger JSON upload shape: {"data": [{"traceID", "spans": [...],
    "processes": {...}}]}.  Timestamps are µs since epoch; parent links
    become CHILD_OF references."""
    processes: Dict[str, Dict] = {}
    proc_ids: Dict[str, str] = {}

    def proc_for(service: str) -> str:
        pid = proc_ids.get(service)
        if pid is None:
            pid = proc_ids[service] = f"p{len(proc_ids) + 1}"
            processes[pid] = {"serviceName": service or "unknown",
                              "tags": []}
        return pid

    jspans = []
    for d in spans:
        refs = []
        if d.get("parent_id"):
            refs.append({"refType": "CHILD_OF", "traceID": trace_id,
                         "spanID": d["parent_id"]})
        tags = [{"key": k, "type": "string", "value": str(v)}
                for k, v in (d.get("tags") or {}).items()]
        logs = [{"timestamp": int(ev["time"] * 1e6),
                 "fields": [{"key": "event", "type": "string",
                             "value": ev["event"]}]}
                for ev in (d.get("events") or [])]
        jspans.append({
            "traceID": trace_id,
            "spanID": d["span_id"],
            "operationName": d.get("name", ""),
            "references": refs,
            "startTime": int(d["start"] * 1e6),
            "duration": max(1, int(d.get("duration", 0.0) * 1e6)),
            "tags": tags,
            "logs": logs,
            "processID": proc_for(d.get("service", "")),
        })
    jspans.sort(key=lambda s: s["startTime"])
    return {"data": [{"traceID": trace_id, "spans": jspans,
                      "processes": processes}]}


async def _gather_asok(asok_dir: str, trace_id: str) -> List[Dict]:
    from ceph_tpu.common.admin_socket import asok_command

    sources = []
    for path in sorted(glob.glob(os.path.join(asok_dir, "*.asok"))):
        try:
            reply = await asok_command(path, "dump_trace",
                                       trace_id=trace_id)
        except Exception as e:  # daemon gone / asok stale: skip, note it
            print(f"warn: {path}: {e}", file=sys.stderr)
            continue
        # label spans with the daemon the socket belongs to when the
        # tracer didn't stamp a service
        name = os.path.basename(path)[:-len(".asok")]
        for d in reply.get("spans", []):
            d.setdefault("service", name)
        sources.append(reply)
    return collect_spans(sources, trace_id)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="export one stitched trace "
                                            "as Jaeger JSON")
    p.add_argument("--asok-dir", required=True,
                   help="directory of daemon .asok sockets")
    p.add_argument("--trace", required=True, help="trace id (hex)")
    p.add_argument("-o", "--out", default="",
                   help="output file (default stdout)")
    args = p.parse_args(argv)
    spans = asyncio.run(_gather_asok(args.asok_dir, args.trace))
    if not spans:
        print(f"no spans found for trace {args.trace}", file=sys.stderr)
        return 1
    doc = to_jaeger(args.trace, spans)
    links = resolve_parents(spans)
    if links.get("__orphans__"):
        print(f"warn: {links['__orphans__']} spans reference parents "
              f"not in the export (ring eviction?)", file=sys.stderr)
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {len(spans)} spans to {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
