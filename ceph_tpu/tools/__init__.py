"""Operator/test CLIs, mirroring the reference's tool surface:
benchmark (ceph_erasure_code_benchmark), non_regression
(ceph_erasure_code_non_regression), bench_suite (qa bench.sh sweep),
and rados (the rados put/get CLI against a vstart cluster)."""

import sys
from typing import Dict, List


def parse_parameters(params: List[str], warn: bool = True) -> Dict[str, str]:
    """-P k=v list -> profile dict.  Values may themselves contain '='
    (lrc layers profiles embed per-layer k=v strings), so split once."""
    profile: Dict[str, str] = {}
    for kv in params:
        if "=" not in kv:
            if warn:
                print(f"--parameter {kv} ignored because it does not "
                      "contain a =", file=sys.stderr)
            continue
        key, value = kv.split("=", 1)
        profile[key] = value
    return profile
