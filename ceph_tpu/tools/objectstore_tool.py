"""Offline object-store surgery (reference src/tools/ceph_objectstore_tool.cc).

Operates on a stopped OSD's BlueStore directory — list objects, dump one
object's data/metadata/xattrs/omap, export/import objects as portable
blobs, remove objects — the recovery-of-last-resort workflow the reference
tool provides.

    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op list
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op info \\
        --pool 1 --oid obj --shard 0
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op export \\
        --pool 1 --oid obj --shard 0 --file out.bin
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op import \\
        --file out.bin
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op remove \\
        --pool 1 --oid obj --shard 0
"""

from __future__ import annotations

import argparse
import json
import pickle
import sys
from typing import Optional

from ceph_tpu.rados.bluestore import BlueStore
from ceph_tpu.rados.store import ShardMeta, Transaction


def op_list(store: BlueStore, pool: Optional[int]) -> int:
    for key in sorted(store._onodes):
        pid, oid, shard = key
        if pool is not None and pid != pool:
            continue
        print(json.dumps({"pool": pid, "oid": oid, "shard": shard}))
    return 0


def op_info(store: BlueStore, pool: int, oid: str, shard: int) -> int:
    key = (pool, oid, shard)
    got = store.read(key)
    if got is None:
        print("object not found", file=sys.stderr)
        return 1
    data, meta = got
    print(json.dumps({
        "pool": pool, "oid": oid, "shard": shard,
        "stored_bytes": len(data),
        "meta": meta.__dict__,
        "xattrs": sorted(store.getattrs(key)),
        "omap_keys": sorted(store.omap_get(key)),
    }, indent=2))
    return 0


def op_export(store: BlueStore, pool: int, oid: str, shard: int,
              path: str) -> int:
    key = (pool, oid, shard)
    got = store.read(key)
    if got is None:
        print("object not found", file=sys.stderr)
        return 1
    data, meta = got
    blob = pickle.dumps({
        "key": key, "data": data, "meta": meta.__dict__,
        "xattrs": store.getattrs(key), "omap": store.omap_get(key),
    }, protocol=5)
    with open(path, "wb") as f:
        f.write(blob)
    print(f"exported {len(data)} bytes to {path}")
    return 0


def op_import(store: BlueStore, path: str) -> int:
    with open(path, "rb") as f:
        blob = pickle.load(f)
    key = tuple(blob["key"])
    txn = Transaction()
    txn.write(key, blob["data"], ShardMeta(**blob["meta"]))
    if blob.get("omap"):
        txn.omap_set(key, blob["omap"])
    store.queue_transaction(txn)
    for name, value in blob.get("xattrs", {}).items():
        store.setattr(key, name, value)
    print(f"imported {key}")
    return 0


def op_remove(store: BlueStore, pool: int, oid: str, shard: int) -> int:
    txn = Transaction()
    txn.delete((pool, oid, shard))
    store.queue_transaction(txn)
    print(f"removed ({pool}, {oid!r}, {shard})")
    return 0


def op_statfs(store: BlueStore) -> int:
    print(json.dumps(store.statfs(), indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="objectstore-tool")
    p.add_argument("--data-path", required=True)
    p.add_argument("--op", required=True,
                   choices=["list", "info", "export", "import", "remove",
                            "statfs"])
    p.add_argument("--pool", type=int, default=None)
    p.add_argument("--oid")
    p.add_argument("--shard", type=int, default=0)
    p.add_argument("--file")
    args = p.parse_args(argv)
    store = BlueStore(args.data_path)
    try:
        if args.op == "list":
            return op_list(store, args.pool)
        if args.op == "statfs":
            return op_statfs(store)
        if args.op == "import":
            return op_import(store, args.file)
        if args.pool is None or args.oid is None:
            print("--pool and --oid required", file=sys.stderr)
            return 2
        if args.op == "info":
            return op_info(store, args.pool, args.oid, args.shard)
        if args.op == "export":
            return op_export(store, args.pool, args.oid, args.shard, args.file)
        if args.op == "remove":
            return op_remove(store, args.pool, args.oid, args.shard)
        return 2
    finally:
        store.close()


if __name__ == "__main__":
    import signal

    signal.signal(signal.SIGPIPE, signal.SIG_DFL)  # behave under | head
    sys.exit(main())
