"""Wire-format round-trip checker (reference src/tools/ceph-dencoder).

The reference dencoder proves every versioned message/structure survives
encode -> decode across versions (backed by the ceph-object-corpus).  This
tool does the same for the framework's message registry: instantiate each
registered type with defaults, encode, decode, compare field dicts; flag
types whose wire version regressed vs a recorded corpus file.

    python -m ceph_tpu.tools.dencoder list
    python -m ceph_tpu.tools.dencoder roundtrip
    python -m ceph_tpu.tools.dencoder corpus --write corpus.json
    python -m ceph_tpu.tools.dencoder corpus --check corpus.json
    python -m ceph_tpu.tools.dencoder golden     # replay corpus/wire

`golden` replays the archived binary frame corpus (corpus/wire/*.frame,
field-for-field) AND the golden old-build frames (corpus/wire/golden/ —
pre-trace v4, pre-qos MOSDOp v5), proving the truncated-tail decode
rule keeps every archived generation decodable."""

from __future__ import annotations

import argparse
import json
import sys

# importing types (+ mgr) populates the registry
import ceph_tpu.mgr.daemon  # noqa: F401
import ceph_tpu.rados.types  # noqa: F401
from ceph_tpu.rados.messenger import _MSG_TYPES, decode_message, encode_payload


def cmd_list() -> int:
    for type_id in sorted(_MSG_TYPES):
        cls = _MSG_TYPES[type_id]
        print(f"{type_id:5d}  v{cls.VERSION}  {cls.__name__}")
    return 0


def cmd_roundtrip() -> int:
    failures = 0
    for type_id in sorted(_MSG_TYPES):
        cls = _MSG_TYPES[type_id]
        msg = cls()
        try:
            payload = encode_payload(msg)
            back = decode_message(type_id, cls.VERSION, payload)
            if back.__dict__ != msg.__dict__:
                print(f"FAIL {cls.__name__}: field mismatch after round-trip")
                failures += 1
        except Exception as e:
            print(f"FAIL {cls.__name__}: {type(e).__name__}: {e}")
            failures += 1
    print(f"{len(_MSG_TYPES) - failures}/{len(_MSG_TYPES)} types round-trip")
    return 1 if failures else 0


def corpus_snapshot() -> dict:
    return {
        cls.__name__: {"type_id": tid, "version": cls.VERSION,
                       "fields": sorted(cls().__dict__)}
        for tid, cls in _MSG_TYPES.items()
    }


def cmd_corpus(write: str = "", check: str = "") -> int:
    if not write and not check:
        print("corpus requires --write FILE or --check FILE", file=sys.stderr)
        return 2
    snap = corpus_snapshot()
    if write:
        with open(write, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
        print(f"corpus written: {len(snap)} types")
        return 0
    with open(check) as f:
        old = json.load(f)
    problems = 0
    for name, rec in old.items():
        cur = snap.get(name)
        if cur is None:
            print(f"REMOVED type {name} (wire id {rec['type_id']})")
            problems += 1
            continue
        if cur["type_id"] != rec["type_id"]:
            print(f"RE-NUMBERED {name}: {rec['type_id']} -> {cur['type_id']}")
            problems += 1
        if cur["version"] < rec["version"]:
            print(f"VERSION REGRESSION {name}: v{rec['version']} -> "
                  f"v{cur['version']}")
            problems += 1
        missing = set(rec["fields"]) - set(cur["fields"])
        if missing:
            # removed fields break decode of old pickled payloads
            print(f"FIELDS REMOVED from {name}: {sorted(missing)}")
            problems += 1
    print(f"corpus check: {problems} problems across {len(old)} types")
    return 1 if problems else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dencoder")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list")
    sub.add_parser("roundtrip")
    sub.add_parser("golden")
    c = sub.add_parser("corpus")
    c.add_argument("--write", default="")
    c.add_argument("--check", default="")
    args = p.parse_args(argv)
    if args.cmd == "list":
        return cmd_list()
    if args.cmd == "roundtrip":
        return cmd_roundtrip()
    if args.cmd == "golden":
        from ceph_tpu.tools.wire_corpus import check

        return check()
    return cmd_corpus(args.write, args.check)


if __name__ == "__main__":
    import signal

    signal.signal(signal.SIGPIPE, signal.SIG_DFL)  # behave under | head
    sys.exit(main())
