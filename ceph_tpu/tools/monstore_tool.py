"""Offline mon-store inspection/repair (reference
src/tools/ceph_monstore_tool.cc).

Dumps a stopped monitor's MonitorDBStore: paxos versions, the committed
cluster state (map epoch, pools, OSDs, config), and can rewrite the store
to a chosen version (the get/rewrite workflow used for disaster recovery).

    python -m ceph_tpu.tools.monstore_tool PATH dump
    python -m ceph_tpu.tools.monstore_tool PATH get-state [VERSION]
    python -m ceph_tpu.tools.monstore_tool PATH rewrite VERSION
"""

from __future__ import annotations

import json
import pickle
import sys

from ceph_tpu.rados.paxos import MonitorDBStore


def dump(store: MonitorDBStore) -> int:
    print(json.dumps({
        "first_committed": store.first_committed,
        "last_committed": store.last_committed,
        "versions": sorted(store.committed),
        "meta": {k: repr(v) for k, v in store.meta.items()},
    }, indent=2))
    return 0


def get_state(store: MonitorDBStore, version: int = 0) -> int:
    v = version or store.last_committed
    blob = store.get(v)
    if blob is None:
        print(f"version {v} not in store", file=sys.stderr)
        return 1
    state = pickle.loads(blob)
    osdmap = state["osdmap"]
    print(json.dumps({
        "paxos_version": v,
        "map_epoch": osdmap.epoch,
        "osds": {i: {"up": o.up, "in": o.in_cluster, "addr": list(o.addr)}
                 for i, o in osdmap.osds.items()},
        "pools": {i: {"name": p.name, "type": p.pool_type, "pg_num": p.pg_num,
                      "profile": p.profile}
                  for i, p in osdmap.pools.items()},
        "cluster_conf": state["cluster_conf"],
    }, indent=2))
    return 0


def rewrite(store: MonitorDBStore, version: int) -> int:
    """Truncate history after `version` (disaster rollback)."""
    blob = store.get(version)
    if blob is None:
        print(f"version {version} not in store", file=sys.stderr)
        return 1
    for v in list(store.committed):
        if v > version:
            del store.committed[v]
    store.last_committed = version
    store._persist()
    print(f"store rewound to version {version}")
    return 0


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    path, cmd = argv[0], argv[1]
    store = MonitorDBStore(path)
    if cmd == "dump":
        return dump(store)
    if cmd == "get-state":
        return get_state(store, int(argv[2]) if len(argv) > 2 else 0)
    if cmd == "rewrite":
        return rewrite(store, int(argv[2]))
    print(f"unknown command {cmd}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    import signal

    signal.signal(signal.SIGPIPE, signal.SIG_DFL)  # behave under | head
    sys.exit(main())
