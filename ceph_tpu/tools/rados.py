"""rados CLI: put/get/rm/ls against a running vstart cluster
(the reference's src/tools/rados minimal surface).

    python -m ceph_tpu.rados.vstart --osds 5          # terminal 1
    python -m ceph_tpu.tools.rados --mon HOST:PORT mkpool data k=4 m=2
    python -m ceph_tpu.tools.rados --mon HOST:PORT put data obj1 ./file
    python -m ceph_tpu.tools.rados --mon HOST:PORT get data obj1 ./out
"""

from __future__ import annotations

import argparse
import asyncio
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="rados object tool")
    p.add_argument("--mon", required=True, help="mon address host:port")
    sub = p.add_subparsers(dest="cmd", required=True)

    mk = sub.add_parser("mkpool")
    mk.add_argument("pool")
    mk.add_argument("profile", nargs="*", help="profile k=v pairs")

    put = sub.add_parser("put")
    put.add_argument("pool")
    put.add_argument("obj")
    put.add_argument("path")

    get = sub.add_parser("get")
    get.add_argument("pool")
    get.add_argument("obj")
    get.add_argument("path")

    rm = sub.add_parser("rm")
    rm.add_argument("pool")
    rm.add_argument("obj")

    ls = sub.add_parser("ls")
    ls.add_argument("pool")

    return p.parse_args(argv)


async def run(args) -> int:
    from ceph_tpu.rados.client import RadosClient

    host, port = args.mon.rsplit(":", 1)
    client = RadosClient((host, int(port)))
    await client.start()
    try:
        await client.refresh_map()
        pools = {p.name: p.pool_id for p in client.osdmap.pools.values()}
        if args.cmd == "mkpool":
            from ceph_tpu.tools import parse_parameters

            profile = parse_parameters(args.profile)
            profile.setdefault("plugin", "jerasure")
            pool_id = await client.create_pool(args.pool, profile=profile)
            print(f"pool {args.pool} created (id {pool_id})")
            return 0
        if args.pool not in pools:
            print(f"pool {args.pool} does not exist", file=sys.stderr)
            return 1
        pool_id = pools[args.pool]
        if args.cmd == "put":
            with open(args.path, "rb") as f:
                data = f.read()
            await client.put(pool_id, args.obj, data)
        elif args.cmd == "get":
            data = await client.get(pool_id, args.obj)
            with open(args.path, "wb") as f:
                f.write(data)
        elif args.cmd == "rm":
            await client.delete(pool_id, args.obj)
        elif args.cmd == "ls":
            for name in await client.list_objects(pool_id):
                print(name)
        return 0
    finally:
        await client.stop()


def main(argv=None) -> int:
    return asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
