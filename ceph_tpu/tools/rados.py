"""rados CLI: put/get/rm/ls against a running vstart cluster
(the reference's src/tools/rados minimal surface).

    python -m ceph_tpu.rados.vstart --osds 5          # terminal 1
    python -m ceph_tpu.tools.rados --mon HOST:PORT mkpool data k=4 m=2
    python -m ceph_tpu.tools.rados --mon HOST:PORT put data obj1 ./file
    python -m ceph_tpu.tools.rados --mon HOST:PORT get data obj1 ./out
"""

from __future__ import annotations

import argparse
import asyncio
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="rados object tool")
    p.add_argument("--mon", required=True, help="mon address host:port")
    p.add_argument("-N", "--namespace", default="",
                   help="rados namespace for object ops (reference "
                        "rados -N; --all-namespaces for ls)")
    p.add_argument("--all-namespaces", action="store_true",
                   help="ls spans every namespace (prints ns/name)")
    sub = p.add_subparsers(dest="cmd", required=True)

    mk = sub.add_parser("mkpool")
    mk.add_argument("pool")
    mk.add_argument("profile", nargs="*", help="profile k=v pairs")

    put = sub.add_parser("put")
    put.add_argument("pool")
    put.add_argument("obj")
    put.add_argument("path")

    get = sub.add_parser("get")
    get.add_argument("pool")
    get.add_argument("obj")
    get.add_argument("path")

    rm = sub.add_parser("rm")
    rm.add_argument("pool")
    rm.add_argument("obj")

    ls = sub.add_parser("ls")
    ls.add_argument("pool")

    mks = sub.add_parser("mksnap", help="create a pool snapshot")
    mks.add_argument("pool")
    mks.add_argument("snap")

    rms = sub.add_parser("rmsnap", help="remove a pool snapshot")
    rms.add_argument("pool")
    rms.add_argument("snap")

    lss = sub.add_parser("lssnap", help="list pool snapshots")
    lss.add_argument("pool")

    rb = sub.add_parser("rollback",
                        help="roll one object back to a pool snapshot")
    rb.add_argument("pool")
    rb.add_argument("obj")
    rb.add_argument("snap")

    be = sub.add_parser("bench", help="reference `rados bench` role")
    be.add_argument("pool")
    be.add_argument("seconds", type=float)
    be.add_argument("mode", choices=("write", "seq"))
    be.add_argument("--object-size", type=int, default=1 << 22)
    be.add_argument("--concurrency", type=int, default=16)
    be.add_argument("--no-cleanup", action="store_true",
                    help="keep written objects (needed before a seq run)")
    be.add_argument("--run-name", default="benchmark_data",
                    help="object name prefix (ties write and seq runs)")

    return p.parse_args(argv)


async def _bench(client, pool_id: int, args) -> int:
    """Timed write/seq workload (reference rados bench: bounded
    concurrency, per-op latency tracking, MB/s summary)."""
    import json
    import os
    import time

    oid = lambda i: f"{args.run_name}_{i:08d}"  # noqa: E731
    payload = os.urandom(args.object_size) if args.mode == "write" else b""
    deadline = time.monotonic() + args.seconds
    lats = []
    issued = 0
    done = 0
    total_bytes = 0
    names: list = []
    sem = asyncio.Semaphore(max(1, args.concurrency))

    async def one(i: int):
        nonlocal done, total_bytes
        t0 = time.monotonic()
        try:
            if args.mode == "write":
                await client.put(pool_id, oid(i), payload)
                nbytes = len(payload)
            else:
                # read the DISCOVERED names, not a regenerated counter:
                # gaps from a partially failed write run must not shift
                # every later read onto a missing object
                nbytes = len(await client.get(pool_id, names[i]))
        except Exception:
            return
        finally:
            sem.release()
        lats.append(time.monotonic() - t0)
        done += 1
        total_bytes += nbytes

    if args.mode == "seq":
        names = sorted(n for n in await client.list_objects(pool_id)
                       if n.startswith(args.run_name + "_"))
        if not names:
            print("no benchmark objects; run "
                  "`bench ... write --no-cleanup` first", file=sys.stderr)
            return 1
    t_start = time.monotonic()
    tasks = []
    # issuance is BOUNDED by the concurrency window (a slot must free
    # before the next op is issued, the reference's in-flight cap): at
    # the deadline at most `concurrency` ops remain to drain
    while time.monotonic() < deadline:
        if args.mode == "seq" and issued >= len(names):
            break
        await sem.acquire()
        if time.monotonic() >= deadline:
            sem.release()
            break
        tasks.append(asyncio.ensure_future(one(issued)))
        issued += 1
        tasks = [t for t in tasks if not t.done()]
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    dt = max(time.monotonic() - t_start, 1e-9)
    total_mb = total_bytes / (1 << 20)  # bytes actually moved
    out = {
        "mode": args.mode,
        "ops": done,
        "seconds": round(dt, 3),
        "bandwidth_MBps": round(total_mb / dt, 3),
        "avg_lat_s": round(sum(lats) / len(lats), 5) if lats else None,
        "max_lat_s": round(max(lats), 5) if lats else None,
    }
    print(json.dumps(out))
    if args.mode == "write" and not args.no_cleanup:
        for i in range(issued):
            try:
                await client.delete(pool_id, oid(i))
            except Exception:
                pass
    return 0


async def run(args) -> int:
    from ceph_tpu.rados.client import RadosClient

    host, port = args.mon.rsplit(":", 1)
    client = RadosClient((host, int(port)))
    await client.start()
    try:
        await client.refresh_map()
        pools = {p.name: p.pool_id for p in client.osdmap.pools.values()}
        if args.cmd == "mkpool":
            from ceph_tpu.tools import parse_parameters

            profile = parse_parameters(args.profile)
            profile.setdefault("plugin", "jerasure")
            pool_id = await client.create_pool(args.pool, profile=profile)
            print(f"pool {args.pool} created (id {pool_id})")
            return 0
        if args.pool not in pools:
            print(f"pool {args.pool} does not exist", file=sys.stderr)
            return 1
        pool_id = pools[args.pool]
        from ceph_tpu.rados.types import (ALL_NSPACES, NS_SEP, SNAP_SEP,
                                          make_oid, split_ns)

        ns = getattr(args, "namespace", "") or ""
        if ns == ALL_NSPACES or NS_SEP in ns or SNAP_SEP in ns:
            # same boundary validation as IoCtx.set_namespace: the
            # reserved separator and the all-namespaces sentinel are
            # not valid I/O namespaces
            print("invalid namespace", file=sys.stderr)
            return 2
        if args.cmd == "put":
            with open(args.path, "rb") as f:
                data = f.read()
            await client.put(pool_id, make_oid(ns, args.obj), data)
        elif args.cmd == "get":
            data = await client.get(pool_id, make_oid(ns, args.obj))
            with open(args.path, "wb") as f:
                f.write(data)
        elif args.cmd == "rm":
            await client.delete(pool_id, make_oid(ns, args.obj))
        elif args.cmd == "ls":
            if args.all_namespaces:
                for wire in await client.list_objects(
                        pool_id, nspace=ALL_NSPACES):
                    w_ns, name = split_ns(wire)
                    print(f"{w_ns}/{name}" if w_ns else name)
            else:
                for wire in await client.list_objects(pool_id, nspace=ns):
                    print(split_ns(wire)[1])
        elif args.cmd == "mksnap":
            sid = await client.pool_snap_create(pool_id, args.snap)
            print(f"created pool {args.pool} snap {args.snap} (id {sid})")
        elif args.cmd == "rmsnap":
            await client.pool_snap_remove(pool_id, args.snap)
            print(f"removed pool {args.pool} snap {args.snap}")
        elif args.cmd == "lssnap":
            snaps = await client.pool_snap_list(pool_id)
            for name, sid in sorted(snaps.items(), key=lambda kv: kv[1]):
                print(f"{sid}\t{name}")
            print(f"{len(snaps)} snaps")
        elif args.cmd == "rollback":
            snaps = await client.pool_snap_list(pool_id)
            if args.snap not in snaps:
                print(f"no snap {args.snap}", file=sys.stderr)
                return 1
            await client.rollback_object(pool_id, make_oid(ns, args.obj),
                                         snaps[args.snap])
            print(f"rolled back {args.obj} to {args.snap}")
        elif args.cmd == "bench":
            return await _bench(client, pool_id, args)
        return 0
    finally:
        await client.stop()


def main(argv=None) -> int:
    return asyncio.run(run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
