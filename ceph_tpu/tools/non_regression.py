"""Non-regression corpus tool: byte-exactness of encodings over time.

Equivalent of the reference's ceph_erasure_code_non_regression
(reference src/test/erasure-code/ceph_erasure_code_non_regression.cc):

    --create  writes <base>/<profile-keyed dir>/{content,0,1,...} with the
              stripe content and every encoded chunk;
    --check   re-encodes the stored content and memcmps every chunk
              (non_regression.cc:252-266), then verifies decode with one
              erasure and with two erasures (:268-284).

The profile-keyed directory name is "plugin=<p> stripe-width=<w> k=v ..."
exactly like the reference (non_regression.cc:116-136), so corpora created
by older versions of this tree keep checking against newer code — the
mechanism that enforces the "parity byte-exact across releases" property.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="erasure code non-regression corpus")
    p.add_argument("--stripe-width", type=int, default=4096)
    p.add_argument("--plugin", default="jerasure")
    p.add_argument("--base", default=".")
    p.add_argument("--parameter", "-P", action="append", default=[])
    p.add_argument("--create", action="store_true")
    p.add_argument("--check", action="store_true")
    # wire-throughput floor (warn-only): compare a fresh BENCH record's
    # daemon_wire_put/get_MBps against the previous round's record
    p.add_argument("--wire-floor", action="store_true")
    p.add_argument("--bench", default="", help="current BENCH json")
    p.add_argument("--prev", default="", help="previous round's BENCH json")
    p.add_argument("--floor", type=float, default=0.8,
                   help="warn when current < floor * previous")
    # chaos smoke (CI): short injected-failure put/get loop against an
    # in-process cluster; exit nonzero on ANY acked-op failure
    p.add_argument("--chaos", action="store_true")
    p.add_argument("--chaos-seconds", type=float, default=6.0,
                   help="length of the chaos put/get loop")
    p.add_argument("--chaos-osds", type=int, default=4)
    # slow-op health smoke (CI): injected dispatch delay must RAISE
    # SLOW_OPS while ops age and the check must CLEAR after recovery;
    # nonzero exit if it never surfaces or wedges raised once idle
    p.add_argument("--slow-ops", action="store_true")
    p.add_argument("--slow-seconds", type=float, default=10.0,
                   help="ceiling on the wait for SLOW_OPS to raise")
    p.add_argument("--slow-osds", type=int, default=3)
    # QoS isolation gate (CI): 3-tenant chaos loop (reserved /
    # best-effort / flooding past its limit) — exit nonzero unless the
    # flooder is the one backoff-shed, the reserved tenant has ZERO
    # acked-op failures, and its p99 stays bounded vs its solo run
    p.add_argument("--qos", action="store_true")
    p.add_argument("--qos-seconds", type=float, default=3.0,
                   help="length of each qos traffic window")
    p.add_argument("--qos-osds", type=int, default=4)
    # crash-telemetry gate (CI): inject a fatal exception into one OSD
    # of a live cluster; a crash report must land in `ceph crash ls`
    # (with the dump_recent ring), RECENT_CRASH must raise in health and
    # clear on `crash archive`, and the cluster log must show the
    # daemon death — nonzero exit otherwise
    p.add_argument("--crash", action="store_true")
    p.add_argument("--crash-seconds", type=float, default=15.0,
                   help="ceiling on each crash-plane wait")
    p.add_argument("--crash-osds", type=int, default=3)
    # tier smoke (CI): promote/evict/read loop against an in-process
    # cluster; exit nonzero on ANY content mismatch between a
    # resident-hit read and the cold decode path for the same object
    p.add_argument("--tier", action="store_true")
    p.add_argument("--tier-seconds", type=float, default=6.0,
                   help="length of the tier promote/evict/read loop")
    p.add_argument("--tier-osds", type=int, default=3)
    # fullness-ladder gate (CI, FAILING): drive nearfull -> backfillfull
    # -> full -> failsafe against a live cluster (injection + a real
    # capacity-bounded store); typed ENOSPC on writes, reads/deletes
    # served, zero acked-op loss, auto-clear after the drain, backfill
    # completing after a backfillfull target frees space
    p.add_argument("--full", action="store_true")
    p.add_argument("--full-seconds", type=float, default=12.0,
                   help="ceiling on each fullness-ladder wait")
    p.add_argument("--full-osds", type=int, default=4)
    # elastic-membership coexistence gate (CI, FAILING): an out ->
    # backfill -> in -> reweight cycle with CONCURRENT deep scrub and
    # reserved-tenant client traffic — zero acked-op loss, byte-identical
    # data after convergence, reserved p99 bounded vs its solo run,
    # plus backfill parking at a backfillfull target and resuming when
    # space frees
    p.add_argument("--rebalance", action="store_true")
    p.add_argument("--rebalance-seconds", type=float, default=20.0,
                   help="ceiling on each membership-cycle wait")
    p.add_argument("--rebalance-osds", type=int, default=4)
    # node-lifecycle thrash (CI): the full membership arc — add a host
    # bucket, crush move, rebalance converges, kill an OSD, auto-out
    # fires (noout honored first), drain, safe-to-destroy flips green,
    # purge, byte-identity sweep — under client traffic with zero
    # acked-op loss, FAILING on any step
    p.add_argument("--lifecycle", action="store_true")
    p.add_argument("--lifecycle-seconds", type=float, default=25.0,
                   help="ceiling on each lifecycle-step wait")
    p.add_argument("--lifecycle-osds", type=int, default=5)
    # pagestore slab-arm parity (CI): the writeback
    # dirty->flush->evict->cold-re-read cycle run once per slab arm
    # (CEPH_TPU_DEVICE_SLAB=1 child vs =0 child, same deterministic
    # content), digests compared byte-for-byte — the device-arm
    # byte-identity gate, FAILING on any divergence
    p.add_argument("--device-parity", action="store_true")
    p.add_argument("--device-parity-child", action="store_true",
                   help="internal: one slab arm's writeback cycle "
                        "(arm picked by CEPH_TPU_DEVICE_SLAB)")
    return p.parse_args(argv)


def profile_directory(args) -> str:
    name = f"plugin={args.plugin} stripe-width={args.stripe_width}"
    for kv in args.parameter:
        name += " " + kv
    return os.path.join(args.base, name)


def build(args):
    from ceph_tpu.ec.registry import registry
    from ceph_tpu.tools import parse_parameters

    profile = {"plugin": args.plugin}
    profile.update(parse_parameters(args.parameter))
    return registry.factory(args.plugin, "", profile)


def run_create(args) -> int:
    codec = build(args)
    directory = profile_directory(args)
    os.makedirs(directory, exist_ok=True)
    rng = np.random.default_rng(0xEC)
    content = rng.integers(0, 256, size=args.stripe_width, dtype=np.uint8).tobytes()
    with open(os.path.join(directory, "content"), "wb") as f:
        f.write(content)
    n = codec.get_chunk_count()
    encoded = codec.encode(set(range(n)), content)
    for chunk, buf in encoded.items():
        with open(os.path.join(directory, str(chunk)), "wb") as f:
            f.write(bytes(buf))
    return 0


def _check_decode(codec, encoded, erasures) -> int:
    available = {c: b for c, b in encoded.items() if c not in erasures}
    chunk_size = len(next(iter(encoded.values())))
    decoded = codec.decode(set(erasures), available, chunk_size)
    for c in erasures:
        if not np.array_equal(decoded[c], encoded[c]):
            print(f"chunk {c} incorrectly recovered", file=sys.stderr)
            return 1
    return 0


def run_check(args) -> int:
    codec = build(args)
    directory = profile_directory(args)
    try:
        with open(os.path.join(directory, "content"), "rb") as f:
            content = f.read()
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 1
    n = codec.get_chunk_count()
    encoded = codec.encode(set(range(n)), content)
    for chunk, buf in encoded.items():
        try:
            with open(os.path.join(directory, str(chunk)), "rb") as f:
                existing = f.read()
        except FileNotFoundError as e:
            print(e, file=sys.stderr)
            return 1
        if existing != bytes(buf):
            print(f"chunk {chunk} encodes differently", file=sys.stderr)
            return 1
    # single erasure: the specific fast path in every plugin
    code = _check_decode(codec, encoded, {0})
    if code:
        return code
    if codec.get_coding_chunk_count() > 1:
        # two erasures: the general case
        code = _check_decode(codec, encoded, {0, n - 1})
        if code:
            return code
    return 0


def _bench_metrics(path: str) -> dict:
    """Flatten a BENCH record: either the raw `bench.py` output dict or
    the round-trajectory shape {"parsed": {...}} the driver archives."""
    import json

    with open(path) as f:
        rec = json.load(f)
    if isinstance(rec, dict) and isinstance(rec.get("parsed"), dict):
        rec = rec["parsed"]
    return rec if isinstance(rec, dict) else {}


def run_wire_floor(args) -> int:
    """FAILING daemon-wire gate, two halves:

    1. Throughput floor: the fresh BENCH record's
       daemon_wire_put/get_MBps against the previous round's — a
       wire-path regression fails CI the round it lands (promoted from
       warn-only now that the multi-lane plane moves the numbers the
       repo's claims rest on).  Skipped when no records are supplied.
    2. Lane byte-identity: an in-process TCP cluster with
       ``ms_lanes_per_peer=4`` + fragmentation must serve every object
       byte-identical to a forced single-lane run of the same payloads —
       the striping/reassembly seam may never change bytes.  Runs
       whenever --wire-floor is requested (no BENCH records needed).

        python -m ceph_tpu.tools.non_regression --wire-floor \\
            [--bench BENCH_rNN.json --prev BENCH_rMM.json]
    """
    rc = 0
    if args.bench and args.prev:
        try:
            cur = _bench_metrics(args.bench)
            prev = _bench_metrics(args.prev)
        except (OSError, ValueError) as e:
            print(f"wire-floor: unreadable BENCH record: {e}",
                  file=sys.stderr)
            return 1
        # like-for-like arms only (ISSUE 12): the headline
        # daemon_wire_* pair rides whichever wirepath arm the host
        # resolved (`wirepath_kind`), so a native-arm record compared
        # against a python-arm record would hide a real wire
        # regression behind the arm speedup (or fail a healthy python
        # host against a native record).  When the arms differ, both
        # records' forced-python numbers (daemon_wire_*_MBps_python,
        # measured every run since ISSUE 12; records older than that
        # ARE the python arm) are the comparable pair.
        ckind = str(cur.get("wirepath_kind") or "python")
        pkind = str(prev.get("wirepath_kind") or "python")
        # like-for-like reactor MODES too (records older than the
        # process-sharded plane are the thread arm): a thread-arm
        # record compared against a process-arm record measures the
        # substrate swap, not a wire regression — skip the throughput
        # half with an explanation instead of failing/greenlighting on
        # an apples-to-oranges pair
        cmode = str(cur.get("reactor_mode") or "thread")
        pmode = str(prev.get("reactor_mode") or "thread")
        if cmode != pmode:
            print(f"wire-floor: reactor_mode differs (cur={cmode} "
                  f"prev={pmode}); skipping the throughput floor — "
                  f"like-for-like modes only (re-run either record "
                  f"with CEPH_TPU_REACTOR={pmode} to compare)")
            lane_rc = _wire_lane_identity()
            return rc or lane_rc
        for key in ("daemon_wire_put_MBps", "daemon_wire_get_MBps"):
            if ckind == pkind:
                c = float(cur.get(key, 0.0) or 0.0)
                p = float(prev.get(key, 0.0) or 0.0)
                label = f"{key} [{ckind} arms]"
            else:
                c = float(cur.get(
                    f"{key}_python" if ckind == "native" else key,
                    0.0) or 0.0)
                p = float(prev.get(
                    f"{key}_python" if pkind == "native" else key,
                    0.0) or 0.0)
                label = (f"{key} [python arms; wirepath_kind differs: "
                         f"cur={ckind} prev={pkind}]")
            if p <= 0:
                print(f"wire-floor: no previous {label}; skipping")
                continue
            if c <= 0:
                rc = 1
                print(f"FAIL wire-floor: {label} missing in the "
                      f"current record")
                continue
            floor = p * args.floor
            if c < floor:
                rc = 1
                print(f"FAIL wire-floor: {label} {c:.1f} MB/s < "
                      f"{args.floor:.2f} x previous {p:.1f} "
                      f"(floor {floor:.1f})")
            else:
                print(f"wire-floor: {label} {c:.1f} MB/s vs previous "
                      f"{p:.1f} ok")
    elif args.bench or args.prev:
        print("wire-floor: need BOTH --bench and --prev for the "
              "throughput half; running lane identity only")
    lane_rc = _wire_lane_identity()
    return rc or lane_rc


def _wire_lane_identity() -> int:
    """Multi-lane vs single-lane byte-identity (the --wire-floor lane
    half): same seeded payloads through a lanes=4 cluster and a forced
    lanes=1 cluster; every get must match the source bytes in both."""
    import asyncio
    import hashlib

    import numpy as np

    from ceph_tpu.rados.vstart import Cluster

    rng = np.random.default_rng(1234)
    payloads = {
        f"obj-{i}": rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        for i, size in enumerate((512, 96 << 10, (1 << 20) + 13,
                                  5 << 20))
    }

    async def serve(lanes: int) -> dict:
        cluster = Cluster(n_osds=4, conf={
            "osd_auto_repair": False,
            "ms_local_fastpath": False,
            "ms_lanes_per_peer": lanes,
        })
        await cluster.start()
        try:
            c = await cluster.client()
            pool = await c.create_pool("lanes", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            out = {}
            for oid, data in payloads.items():
                await c.put(pool, oid, data)
            for oid in payloads:
                got = await c.get(pool, oid)
                out[oid] = hashlib.sha256(bytes(got)).hexdigest()
            await c.stop()
            return out
        finally:
            await cluster.stop()

    want = {oid: hashlib.sha256(data).hexdigest()
            for oid, data in payloads.items()}
    multi = asyncio.run(serve(4))
    single = asyncio.run(serve(1))
    bad = 0
    for oid in payloads:
        if multi.get(oid) != want[oid]:
            print(f"FAIL wire-floor: lanes=4 read of {oid} not "
                  f"byte-identical to source", file=sys.stderr)
            bad += 1
        if single.get(oid) != want[oid]:
            print(f"FAIL wire-floor: lanes=1 read of {oid} not "
                  f"byte-identical to source", file=sys.stderr)
            bad += 1
    if bad:
        return 1
    print(f"wire-floor: {len(payloads)} objects byte-identical across "
          f"multi-lane (4) and single-lane runs")
    return 0


def run_chaos(args) -> int:
    """Chaos smoke mode (CI): hammer put/get against an in-process
    cluster with socket-failure + duplicate-frame injection for a few
    seconds; ANY acked-op failure — a put that raises despite the client
    resilience layer, or an acked write that does not read back
    byte-identical — exits nonzero.  The acceptance bar of the op-
    resilience layer (resend-on-map-change, MOSDBackoff, reqid dedup),
    runnable as one command:

        python -m ceph_tpu.tools.non_regression --chaos
    """
    import asyncio
    import os as _os

    from ceph_tpu.rados.vstart import Cluster

    async def go() -> int:
        conf = {"osd_auto_repair": True, "osd_repair_delay": 0.2,
                "osd_heartbeat_interval": 0.2,
                "mon_osd_report_grace": 1.0,
                "ms_inject_socket_failures": 80,
                "ms_inject_dup_frames": 20}
        cluster = Cluster(n_osds=max(3, args.chaos_osds), conf=conf)
        await cluster.start()
        failures = []
        try:
            c = await cluster.client()
            pool = await c.create_pool("chaos", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            acked = {}
            import time as _time

            deadline = _time.monotonic() + args.chaos_seconds
            i = 0
            while _time.monotonic() < deadline:
                oid = f"c{i % 16}"
                blob = _os.urandom(3000 + (i % 512))
                try:
                    await c.put(pool, oid, blob)
                    acked[oid] = blob
                except Exception as e:
                    failures.append(f"acked-op failure: put {oid}: {e}")
                if acked and i % 3 == 0:
                    roid = sorted(acked)[i % len(acked)]
                    try:
                        got = await c.get(pool, roid)
                        if got != acked[roid]:
                            failures.append(
                                f"readback mismatch on {roid}")
                    except Exception as e:
                        failures.append(f"read {roid} failed: {e}")
                i += 1
            print(f"chaos: {i} ops, {len(acked)} objects, "
                  f"{len(failures)} failures; objecter: "
                  f"{ {k: v for k, v in c.perf.dump().items() if isinstance(v, int)} }")
            await c.stop()
        finally:
            await cluster.stop()
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_slow_ops(args) -> int:
    """Slow-op health smoke (CI): a chaos loop under
    CEPH_TPU_INJECT_DISPATCH_DELAY — every device dispatch sleeps, so
    in-flight writes age past osd_op_complaint_time and the OSDs'
    ping-borne health reports must RAISE the mon's SLOW_OPS check; when
    the injection stops and the backlog drains, the check must CLEAR
    within about one complaint interval (plus the ping cadence).
    Nonzero exit if a slow op never surfaces, or if the check wedges
    raised after the cluster is idle.  The acceptance bar of the health
    model, runnable as one command:

        python -m ceph_tpu.tools.non_regression --slow-ops
    """
    import asyncio
    import os as _os
    import time as _time

    # the batching queue (the injection point) engages only on an
    # accelerator backend; FORCE_BATCH is the sanctioned CPU override —
    # set BEFORE any OSD asks for the shared queue
    _os.environ["CEPH_TPU_FORCE_BATCH"] = "1"
    _os.environ.setdefault("CEPH_TPU_INJECT_DISPATCH_DELAY", "0.6")

    from ceph_tpu.rados.vstart import Cluster
    import ceph_tpu.rados.osd as osdmod

    complaint = 0.25

    async def go() -> int:
        conf = {"osd_auto_repair": False,
                "osd_heartbeat_interval": 0.1,
                "mon_osd_report_grace": 5.0,
                "client_op_timeout": 30.0,
                "client_op_deadline": 120.0,
                "osd_op_complaint_time": complaint}
        cluster = Cluster(n_osds=max(3, args.slow_osds), conf=conf)
        await cluster.start()
        failures = []
        try:
            c = await cluster.client()
            pool = await c.create_pool("slow", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            q = osdmod.shared_batching_queue()
            if q is None:
                print("FAIL batching queue did not engage under "
                      "CEPH_TPU_FORCE_BATCH=1", file=sys.stderr)
                return 1
            delay = float(_os.environ["CEPH_TPU_INJECT_DISPATCH_DELAY"])
            q.inject_dispatch_delay = delay
            loop = asyncio.get_running_loop()
            # a standing burst of writes: each one's encode dispatch
            # sleeps `delay`, so in-flight ops age past the complaint
            tasks = [loop.create_task(
                c.put(pool, f"s{i}", _os.urandom(60_000 + 512 * i)))
                for i in range(8)]
            raised = False
            deadline = _time.monotonic() + args.slow_seconds
            while _time.monotonic() < deadline:
                h = await c.get_health(detail=True)
                if "SLOW_OPS" in (h.get("checks") or {}):
                    chk = h["checks"]["SLOW_OPS"]
                    print(f"slow-ops raised: {chk['summary']} "
                          f"(oldest {chk.get('oldest_age', 0):.2f}s)")
                    raised = True
                    break
                await asyncio.sleep(0.05)
            if not raised:
                failures.append("SLOW_OPS never raised under injected "
                                "dispatch delay")
            # recovery: stop the injection, drain the backlog
            q.inject_dispatch_delay = 0.0
            got = await asyncio.gather(*tasks, return_exceptions=True)
            for g in got:
                if isinstance(g, Exception):
                    failures.append(f"write failed under delay: {g}")
            # the check must clear within ~one complaint interval after
            # the cluster idles (next ping carries an empty report)
            cleared = False
            clear_deadline = _time.monotonic() + complaint + 3.0
            while _time.monotonic() < clear_deadline:
                h = await c.get_health()
                if "SLOW_OPS" not in (h.get("checks") or {}):
                    cleared = True
                    break
                await asyncio.sleep(0.05)
            if raised and not cleared:
                failures.append("SLOW_OPS wedged raised after the "
                                "cluster went idle")
            if cleared:
                print("slow-ops cleared after recovery")
            await c.stop()
        finally:
            await cluster.stop()
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_qos(args) -> int:
    """QoS isolation gate (CI): three tenant classes — one RESERVED
    (qos_class:gold, guaranteed IOPS), one BEST-EFFORT (the pool's
    default client profile), one FLOODING past its declared limit (48
    unpaced workers against qos_limit 30/s) — hammer one pool through
    separate client processes, with every read content-verified.  The
    acceptance bar of the multi-tenant QoS subsystem, runnable as one
    command:

        python -m ceph_tpu.tools.non_regression --qos

    Nonzero exit when any of these fail:
      - the FLOODER (and only the flooder) is backoff-shed: the OSDs'
        qos_shed counters moved and the flooder's client received
        MOSDBackoff blocks while the reserved client received at most a
        bootstrap handful (the legacy shed window before the flooder's
        arrears cross osd_qos_shed_grace)
      - the reserved tenant's acked-op failures are exactly 0 (and all
        its reads were byte-identical)
      - the reserved tenant's contended get p99 stays bounded:
        <= max(3x its solo-run p99, 1.5x the best-effort class's
        contended p99, 200ms).  The best-effort term matters on 1-2
        core CI hosts: the contended window inflates EVERY op's latency
        through process-wide CPU contention (one event loop carries the
        whole in-process cluster), which QoS cannot remove — but a real
        isolation regression (the reserved class being shed/starved)
        shows up as gold >> best-effort in the SAME window, and 0.5s
        backoff parks blow straight past every term of the bound.
    """
    import asyncio

    from ceph_tpu.rados.client import RadosClient
    from ceph_tpu.rados.vstart import Cluster
    from ceph_tpu.tools.traffic import TenantClass, TrafficHarness

    flood_limit = 30.0

    async def go() -> int:
        conf = {"osd_auto_repair": False,
                "ms_local_fastpath": False,
                "osd_op_queue": "mclock",
                "osd_backoff_queue_depth": 6,
                "osd_qos_shed_grace": 0.05,
                "osd_backoff_secs": 0.5,
                "client_op_timeout": 30.0,
                "client_op_deadline": 60.0}
        cluster = Cluster(n_osds=max(3, args.qos_osds), conf=conf)
        await cluster.start()
        failures = []
        try:
            c0 = await cluster.client()
            pool = await c0.create_pool("qos", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            await c0.pool_set(pool, "qos_reservation", "50")
            await c0.pool_set(pool, "qos_weight", "5")
            await c0.pool_set(pool, "qos_class:gold", "100:20:0")
            await c0.pool_set(pool, "qos_class:flood",
                              f"0:1:{flood_limit:g}")
            c_gold = await cluster.client()
            c_be = await cluster.client()
            fconf = dict(cluster.conf)
            fconf["client_op_deadline"] = 5.0  # a shed flooder times out
            c_flood = RadosClient(cluster.mon_addrs, fconf)
            await c_flood.start()
            await c_flood.refresh_map()
            gold = TenantClass("gold", c_gold, tenants=1, workers=4,
                              rate=40.0)
            be = TenantClass("", c_be, tenants=64, workers=2, rate=20.0)
            flood = TenantClass("flood", c_flood, tenants=1, workers=48,
                                rate=0.0)
            h = TrafficHarness([gold, be, flood], pool, n_objects=32,
                               obj_size=16 << 10, verify=True)
            await h.preload()
            solo = await h.run_phase("solo", args.qos_seconds, 0.25,
                                     classes=[gold])
            for attempt in range(2):
                shed0 = sum(o.sched_perf.get("qos_shed")
                            for o in cluster.osds.values())
                fb0 = c_flood.perf.get("backoffs_received")
                cont = await h.run_phase("contended", args.qos_seconds,
                                         0.25)
                sheds = sum(o.sched_perf.get("qos_shed")
                            for o in cluster.osds.values()) - shed0
                flood_backoffs = c_flood.perf.get(
                    "backoffs_received") - fb0
                if sheds or flood_backoffs:
                    break
                # saturation never engaged AT ALL (no shed, no block):
                # on a 1-2 core CI host a noisy neighbor can stall the
                # whole in-process event loop so no op volume ever
                # builds — one retry; a real regression (shed machinery
                # broken) reproduces and still fails
                print("qos: saturation never engaged; retrying the "
                      "contended window once (host stall suspected)")
            solo_s, cont_s = solo.summary(), cont.summary()
            gold_solo = solo_s.get("gold", {})
            gold_cont = cont_s.get("gold", {})
            solo_p99 = gold_solo.get("get", {}).get("p99_us", 0.0)
            cont_p99 = gold_cont.get("get", {}).get("p99_us", 0.0)
            be_p99 = cont_s.get("default", {}).get("get", {}).get(
                "p99_us", 0.0)
            gold_backoffs = c_gold.perf.get("backoffs_received")
            gold_fail = (gold_solo.get("failures", 0)
                         + gold_cont.get("failures", 0))
            if sheds <= 0:
                failures.append("no qos-directed shed ever happened "
                                "(qos_shed stayed 0 under a flooder)")
            if flood_backoffs <= 0:
                failures.append("the flooding client never received an "
                                "MOSDBackoff block")
            if gold_fail:
                failures.append(f"reserved tenant had {gold_fail} "
                                "acked-op failures (must be 0)")
            if gold_backoffs > 2:
                failures.append(
                    f"reserved tenant was backoff-shed {gold_backoffs} "
                    "times (the shed must target the flooder; <=2 "
                    "bootstrap blocks tolerated)")
            bound = max(3.0 * solo_p99, 1.5 * be_p99, 200_000.0)
            if not solo_p99 or not cont_p99:
                failures.append("reserved tenant percentiles missing "
                                f"(solo={solo_p99}, contended={cont_p99})")
            elif cont_p99 > bound:
                failures.append(
                    f"reserved get p99 unbounded under flood: "
                    f"{cont_p99:.0f}us > max(3x solo {solo_p99:.0f}us, "
                    f"1.5x best-effort {be_p99:.0f}us, 200ms)")
            print(f"qos: solo p99 {solo_p99:.0f}us, contended p99 "
                  f"{cont_p99:.0f}us (best-effort {be_p99:.0f}us), "
                  f"sheds {sheds}, flooder backoffs "
                  f"{flood_backoffs}, reserved backoffs {gold_backoffs}, "
                  f"flood served {cont_s.get('flood', {}).get('ops', 0)} "
                  f"ops (limit {flood_limit:g}/s), "
                  f"{len(failures)} failures")
            for c in (c0, c_gold, c_be, c_flood):
                await c.stop()
        finally:
            await cluster.stop()
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_crash(args) -> int:
    """Crash-telemetry gate (CI): the acceptance bar of the cluster-log
    + crash plane, runnable as one command:

        python -m ceph_tpu.tools.non_regression --crash

    Injects a fatal exception into one OSD of a live cluster and then
    asserts, in order: a crash report lands in `ceph crash ls` whose
    `crash info` carries the injected exception, a backtrace, and the
    daemon's dump_recent ring; `ceph health detail` raises RECENT_CRASH;
    the cluster log records the daemon death (and the mon's subsequent
    mark-down); `crash archive` clears RECENT_CRASH.  Any miss exits
    nonzero."""
    import asyncio
    import time as _time

    from ceph_tpu.rados.vstart import Cluster

    async def go() -> int:
        conf = {"osd_auto_repair": False,
                "osd_heartbeat_interval": 0.1,
                "mon_osd_report_grace": 1.0}
        cluster = Cluster(n_osds=max(2, args.crash_osds), conf=conf)
        await cluster.start()
        failures = []
        try:
            c = await cluster.client()
            pool = await c.create_pool("crash", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            # some traffic first, so the victim's dump_recent ring has
            # history worth spooling
            import os as _os

            for i in range(4):
                await c.put(pool, f"o{i}", _os.urandom(8192))
            victim = sorted(cluster.osds)[-1]
            cluster.osds[victim].inject_crash()
            # 1) the crash report must land in `ceph crash ls`
            report = None
            deadline = _time.monotonic() + args.crash_seconds
            while _time.monotonic() < deadline:
                ls = await c.crash_ls()
                mine = [r for r in ls if r["entity"] == f"osd.{victim}"]
                if mine:
                    report = mine[-1]
                    break
                await asyncio.sleep(0.1)
            if report is None:
                failures.append(f"no crash report for osd.{victim} in "
                                f"`crash ls` after injection")
            else:
                info = await c.crash_info(report["crash_id"])
                if "injected crash" not in info.get("exception", ""):
                    failures.append("crash info lost the exception: "
                                    f"{info.get('exception')!r}")
                if "Traceback" not in info.get("backtrace", ""):
                    failures.append("crash info carries no backtrace")
                if not info.get("recent"):
                    failures.append("crash info carries no dump_recent "
                                    "ring")
            # 2) RECENT_CRASH raises in health detail
            raised = False
            deadline = _time.monotonic() + args.crash_seconds
            while _time.monotonic() < deadline:
                h = await c.get_health(detail=True)
                if "RECENT_CRASH" in (h.get("checks") or {}):
                    raised = True
                    break
                await asyncio.sleep(0.1)
            if not raised:
                failures.append("RECENT_CRASH never raised in "
                                "`health detail`")
            # 3) the cluster log shows the daemon death
            deadline = _time.monotonic() + args.crash_seconds
            crash_line = down_line = False
            while _time.monotonic() < deadline:
                tail = await c.log_last(level=3)  # warn+
                crash_line = any("crashed" in e.message
                                 and f"osd.{victim}" in e.message
                                 for e in tail)
                down_line = any("marked down" in e.message
                                and f"osd.{victim}" in e.message
                                for e in tail)
                if crash_line and down_line:
                    break
                await asyncio.sleep(0.1)
            if not crash_line:
                failures.append("cluster log has no crash entry for "
                                f"osd.{victim}")
            if not down_line:
                failures.append("cluster log has no mark-down entry for "
                                f"osd.{victim}")
            # 4) archive clears RECENT_CRASH
            if report is not None:
                await c.crash_archive(report["crash_id"])
                h = await c.get_health()
                if "RECENT_CRASH" in (h.get("checks") or {}):
                    failures.append("RECENT_CRASH still raised after "
                                    "`crash archive`")
            print(f"crash: victim osd.{victim}, report "
                  f"{'found' if report else 'MISSING'}, "
                  f"RECENT_CRASH {'raised' if raised else 'MISSING'}, "
                  f"clog crash/{crash_line} down/{down_line}, "
                  f"{len(failures)} failures")
            await c.stop()
        finally:
            await cluster.stop()
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_tier(args) -> int:
    """Tier smoke mode (CI): a promote/evict/read loop against an
    in-process cluster with the device-residency tier forced on.  Every
    iteration reads one hot object through BOTH paths — the cold decode
    path (residents dropped first) and, after promotion, the
    resident-hit fast path — and exits nonzero on ANY content mismatch
    between the two (the tier's byte-identity gate), on any read
    failure, and on the agent failing to bound resident bytes.  The
    acceptance bar of the cache tier, runnable as one command:

        python -m ceph_tpu.tools.non_regression --tier
    """
    import asyncio
    import os as _os

    # the planar store (and with it promotion) engages only on an
    # accelerator backend; FORCE_BATCH is the sanctioned CPU override —
    # set BEFORE any OSD asks for the shared queue
    _os.environ["CEPH_TPU_FORCE_BATCH"] = "1"

    from ceph_tpu.rados.vstart import Cluster
    import ceph_tpu.rados.osd as osdmod

    target_bytes = 3 << 20

    async def go() -> int:
        conf = {"osd_auto_repair": False, "client_op_timeout": 60.0,
                "osd_heartbeat_interval": 0.1,
                "osd_hit_set_period": 0.5,
                "osd_min_read_recency_for_promote": 1,
                "osd_tier_agent_interval": 0.1,
                "osd_tier_target_max_bytes": target_bytes,
                "osd_cache_target_full_ratio": 0.8,
                # writeback legs: dirty residents must flush on the
                # agent cadence (age-driven) so dirty_pages is bounded
                # after settling — the failing gate below
                "osd_tier_flush_age": 0.3}
        # 4-OSD floor: the kill-primary leg needs a SPARE device — the
        # mon auto-outs the dead OSD (mon_osd_down_out_interval) and
        # CRUSH rebuilds a full acting set, but only if one exists
        # (k+m == n_osds leaves a hole no auto-out can fill)
        cluster = Cluster(n_osds=max(4, args.tier_osds), conf=conf)
        await cluster.start()
        failures = []
        resident_reads = cold_reads = 0
        try:
            c = await cluster.client()
            pool = await c.create_pool("tier", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            store = osdmod.shared_planar_store()
            if store is None:
                print("FAIL planar store did not engage under "
                      "CEPH_TPU_FORCE_BATCH=1", file=sys.stderr)
                return 1
            import time as _time

            blobs = {}
            # hot set larger than the agent target: evictions must run
            for i in range(24):
                oid = f"h{i}"
                blobs[oid] = _os.urandom(150_000 + 512 * i)
                await c.put(pool, oid, blobs[oid])

            def drop_residents(oid: str) -> None:
                for o in cluster.osds.values():
                    if o._planar is not None:
                        o._planar.drop(o._planar_key(pool, oid))

            def resident_on(oid: str) -> bool:
                return any(o._planar is not None
                           and o._planar_key(pool, oid) in store
                           for o in cluster.osds.values())

            deadline = _time.monotonic() + args.tier_seconds
            i = 0
            while _time.monotonic() < deadline:
                oid = f"h{i % len(blobs)}"
                want = blobs[oid]
                # COLD path: force the decode pipeline
                drop_residents(oid)
                try:
                    cold = await c.get(pool, oid, fadvise="dontneed")
                    cold_reads += 1
                    if cold != want:
                        failures.append(f"cold-path mismatch on {oid}")
                except Exception as e:
                    failures.append(f"cold read {oid} failed: {e}")
                    i += 1
                    continue
                # PROMOTE (willneed bypasses recency, not the throttle)
                # then read the resident-hit path
                try:
                    await c.get(pool, oid, fadvise="willneed")
                    for _ in range(50):
                        if resident_on(oid):
                            break
                        await asyncio.sleep(0.01)
                    hot = await c.get(pool, oid)
                    if resident_on(oid):
                        resident_reads += 1
                    if hot != cold:
                        failures.append(
                            f"resident-hit vs cold mismatch on {oid}")
                    if hot != want:
                        failures.append(f"resident-hit mismatch on {oid}")
                except Exception as e:
                    failures.append(f"hot read {oid} failed: {e}")
                if i % 7 == 3:
                    # churn: overwrite invalidates the resident; the next
                    # round must serve the NEW bytes on both paths
                    blobs[oid] = _os.urandom(140_000 + 256 * i)
                    await c.put(pool, oid, blobs[oid])
                i += 1
            # bounded residency: the agent must be holding the line.
            # Settle for a few agent intervals first — the loop above
            # promotes flat-out and the agent enforces on its cadence,
            # so an instantaneous sample can catch promotions that
            # landed since the last pass (by-design transient, same as
            # the reference agent)
            await asyncio.sleep(0.5)
            if store.resident_bytes > target_bytes:
                failures.append(
                    f"resident_bytes {store.resident_bytes} exceeds "
                    f"target {target_bytes} after settling")
            # -- writeback legs (paged store only): put under
            # cache_mode=writeback -> dirty pages -> agent flush ->
            # evict -> re-read byte identity, with bounded dirty_pages
            # after settling as the failing gate
            if hasattr(store, "dirty_items"):
                await c.pool_set(pool, "cache_mode", "writeback")
                for o in cluster.osds.values():
                    # pool-opt propagation: poll each OSD's map
                    for _ in range(100):
                        p = (o.osdmap.pools.get(pool)
                             if o.osdmap else None)
                        if p is not None and (getattr(p, "opts", {})
                                              or {}).get("cache_mode") \
                                == "writeback":
                            break
                        await asyncio.sleep(0.02)
                wb_blobs = {}
                saw_dirty = False
                pinned = {}
                for i in range(6):
                    oid = f"wb{i}"
                    wb_blobs[oid] = _os.urandom(120_000 + 1024 * i)
                    await c.put(pool, oid, wb_blobs[oid])
                    # sample dirt per put: the agent (0.1s cadence,
                    # 0.3s flush age) may legitimately drain earlier
                    # puts' pages while later puts run on a slow host —
                    # an after-the-loop snapshot would false-fail
                    saw_dirty = saw_dirty or store.dirty_pages > 0
                    for key, info, _g, _s in store.dirty_items():
                        if info is not None:
                            pinned[key] = info
                pinned = sorted(pinned.items())
                if not saw_dirty or not pinned:
                    failures.append(
                        "writeback puts left no dirty pages (writeback "
                        "never engaged)")
                for oid, want in wb_blobs.items():
                    got = await c.get(pool, oid)
                    if got != want:
                        failures.append(
                            f"writeback resident read mismatch on {oid}")
                # agent settling: age-driven flush must bound dirty
                for _ in range(100):
                    if not store.has_dirty():
                        break
                    await asyncio.sleep(0.05)
                if store.dirty_pages != 0:
                    failures.append(
                        f"dirty_pages {store.dirty_pages} not bounded "
                        f"after agent settling (flush never drained)")
                # the deferred local applies LANDED at their versions.
                # A WritebackRecord pins its deferred local shards; a
                # fast-ack CacheDirtyRecord defers the WHOLE k+m encode
                # (the flush lands the installer's acting shards), and
                # its ADOPTED copies on cache peers pin nothing locally.
                for key, info in pinned:
                    osd = cluster.osds.get(key[0])
                    if osd is None:
                        continue
                    shards = getattr(info, "shards", None)
                    if shards is None:
                        if getattr(info, "primary", key[0]) != key[0]:
                            continue  # adopted copy: owner destages
                        p = osd.osdmap.pools[info.pool_id]
                        acting = osd.osdmap.pg_to_acting(p, info.pg)
                        shards = [s for s, o_id in enumerate(acting)
                                  if o_id == key[0]]
                    for shard in shards:
                        got_s = osd._store_read(
                            (info.pool_id, info.oid, shard))
                        if got_s is None or got_s[1].version < info.version:
                            failures.append(
                                f"flush of {info.oid} shard {shard} on "
                                f"osd.{key[0]} never reached the store")
                # evict everything, then cold re-reads must serve the
                # flushed bytes (flush-before-evict byte identity)
                for oid in wb_blobs:
                    drop_residents(oid)
                for oid, want in wb_blobs.items():
                    got = await c.get(pool, oid, fadvise="dontneed")
                    if got != want:
                        failures.append(
                            f"post-flush cold read mismatch on {oid}")
                wb_perf = store.perf.dump()
                print(f"tier writeback: {len(wb_blobs)} puts, "
                      f"flushes={wb_perf.get('flushes', 0)} "
                      f"flush_bytes={wb_perf.get('flush_bytes', 0)} "
                      f"dirty_pages={store.dirty_pages} "
                      f"page_evictions={wb_perf.get('page_evictions', 0)} "
                      f"frag_saved={wb_perf.get('frag_saved_bytes', 0)}")
                # -- kill-primary-before-flush (the fast-ack durability
                # gate): a put acked at the CACHE quorum, its primary
                # SIGKILLed before any flush, must survive — a replica
                # replays its raw dirty copy to the PG's new primary,
                # who destages it; the cold re-read is byte-identical
                for o in cluster.osds.values():
                    o.conf["osd_tier_flush_age"] = 120.0  # park dirt
                kp_blob = _os.urandom(130_000)
                await c.put(pool, "wbkill", kp_blob)
                owned = [(k, info) for k, info, _g, _s
                         in store.dirty_items()
                         if info is not None and info.oid == "wbkill"
                         and getattr(info, "primary", None) == k[0]]
                if not owned:
                    failures.append(
                        "kill-primary leg: fast-ack put left no owned "
                        "raw dirty record (fast ack never engaged)")
                else:
                    (kp_key, kp_rec), = owned
                    adopters = [p for p in kp_rec.peers
                                if p != kp_key[0]
                                and store.is_dirty((p, pool, "wbkill"))]
                    if not adopters:
                        failures.append(
                            "kill-primary leg: no cache peer adopted "
                            "the dirty copy before the kill")
                    await cluster.kill_osd(kp_key[0])
                    got_kp = None
                    for _ in range(300):
                        await asyncio.sleep(0.1)
                        try:
                            got_kp = await c.get(pool, "wbkill")
                            if got_kp == kp_blob:
                                break
                        except Exception:
                            continue
                    if got_kp != kp_blob:
                        failures.append(
                            "kill-primary leg: acked write lost after "
                            "primary SIGKILL before flush")
                    # the survivors' replay destaged and released the
                    # adopted copies
                    for _ in range(100):
                        if not any(info is not None
                                   and info.oid == "wbkill"
                                   for _k, info, _g, _s
                                   in store.dirty_items()):
                            break
                        await asyncio.sleep(0.1)
                    if any(info is not None and info.oid == "wbkill"
                           for _k, info, _g, _s in store.dirty_items()):
                        failures.append(
                            "kill-primary leg: adopted dirty copies "
                            "never destaged after the failover")
                    drop_residents("wbkill")
                    try:
                        cold_kp = await c.get(pool, "wbkill",
                                              fadvise="dontneed")
                        if cold_kp != kp_blob:
                            failures.append(
                                "kill-primary leg: cold re-read after "
                                "replay is not byte-identical")
                    except Exception as e:
                        failures.append(
                            f"kill-primary leg: cold re-read failed: {e}")
                    tier_enc = sum(o.tier_perf.get("flush_encodes")
                                   for o in cluster.osds.values())
                    print(f"tier kill-primary: victim osd.{kp_key[0]}, "
                          f"{len(adopters)} adopter(s), replay "
                          f"flush_encodes={tier_enc}, re-read "
                          f"{'ok' if got_kp == kp_blob else 'LOST'}")
                for o in cluster.osds.values():
                    o.conf["osd_tier_flush_age"] = 0.3
            else:
                print("tier writeback: SKIPPED (monolithic resident "
                      "store forced; writeback needs the pagestore)")
            tier = {}
            for o in cluster.osds.values():
                for k, v in o.tier_perf.dump().items():
                    if isinstance(v, int):
                        tier[k] = tier.get(k, 0) + v
            print(f"tier: {i} iterations, {resident_reads} resident-hit "
                  f"reads, {cold_reads} cold reads, "
                  f"{len(failures)} failures; "
                  f"promote={tier.get('promote', 0)} "
                  f"evict={tier.get('agent_evict', 0)} "
                  f"evict_noop={tier.get('agent_evict_noop', 0)} "
                  f"resident_hit={tier.get('resident_hit', 0)} "
                  f"throttled={tier.get('promote_throttled', 0)}")
            if not resident_reads:
                failures.append("no resident-hit read ever happened "
                                "(promotion never engaged)")
            await c.stop()
        finally:
            await cluster.stop()
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_full(args) -> int:
    """Fullness-ladder gate (CI), the acceptance bar of the capacity
    plane, runnable as one FAILING command:

        python -m ceph_tpu.tools.non_regression --full

    Three legs:

    1. INJECTED LADDER (no gigabytes written): force one OSD's reported
       utilization through nearfull -> full; assert OSD_NEARFULL warns,
       OSD_FULL + POOL_FULL raise, writes into PGs holding the full OSD
       fail TYPED ENOSPC, reads of every acked object stay
       byte-identical (zero acked-op loss), deletes are still served;
       clear the injection and assert the flags auto-clear and writes
       resume.
    2. REAL CAPACITY: a store with a genuine byte ceiling fills until
       the failsafe refuses (typed ENOSPC, store untouched); deleting
       drains below the ratio, states auto-clear, writes resume —
       the delete-is-the-way-out contract on real bytes.
    3. BACKFILLFULL: a backfill whose target is past its backfillfull
       ratio parks as `backfill_toofull` (PG_BACKFILL_FULL in health);
       freeing the target lets the backfill complete with data intact.
    """
    import asyncio
    import errno as _errno
    import os as _os
    import time as _time

    from ceph_tpu.rados.client import RadosError
    from ceph_tpu.rados.vstart import Cluster

    async def wait_for(pred, seconds, what, failures):
        deadline = _time.monotonic() + seconds
        while _time.monotonic() < deadline:
            if await pred():
                return True
            await asyncio.sleep(0.1)
        failures.append(f"timed out waiting for {what}")
        return False

    async def verify_acked(c, pool, acked, failures, stage):
        """Zero acked-op loss: every acked object reads byte-identical."""
        for oid, want in acked.items():
            try:
                got = await c.get(pool, oid)
            except Exception as e:
                failures.append(f"[{stage}] acked {oid} unreadable: {e}")
                continue
            if bytes(got) != want:
                failures.append(f"[{stage}] acked {oid} corrupted")

    async def leg_injected(failures) -> None:
        conf = {"osd_auto_repair": True, "osd_heartbeat_interval": 0.1,
                "mon_osd_report_grace": 2.0,
                "client_op_timeout": 5.0, "client_op_deadline": 6.0}
        cluster = Cluster(n_osds=max(3, args.full_osds), conf=conf)
        await cluster.start()
        try:
            c = await cluster.client()
            pool = await c.create_pool("fullpool", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            acked = {}
            for i in range(10):
                blob = _os.urandom(48_000 + 997 * i)
                await c.put(pool, f"o{i}", blob)
                acked[f"o{i}"] = blob
            victim = sorted(cluster.osds)[0]

            async def state_is(want):
                h = await c.get_health(detail=True)
                util = h.get("osd_utilization") or {}
                return (util.get(victim) or {}).get("state") == want

            # nearfull: warn raises, writes still flow
            cluster.conf["osd_debug_inject_full"] = f"{victim}:0.87"
            await wait_for(lambda: state_is("nearfull"), args.full_seconds,
                           "nearfull state", failures)
            h = await c.get_health()
            if "OSD_NEARFULL" not in (h.get("checks") or {}):
                failures.append("OSD_NEARFULL never raised")
            await c.put(pool, "nearfull-write", b"x" * 1000)
            acked["nearfull-write"] = b"x" * 1000
            # full: OSD_FULL(+POOL_FULL) raise; writes typed-ENOSPC
            cluster.conf["osd_debug_inject_full"] = f"{victim}:0.96"
            await wait_for(lambda: state_is("full"), args.full_seconds,
                           "full state", failures)
            h = await c.get_health()
            for check in ("OSD_FULL", "POOL_FULL"):
                if check not in (h.get("checks") or {}):
                    failures.append(f"{check} never raised")
            # an oid whose PG's acting set holds the victim
            await c.refresh_map()
            p = c.osdmap.pools[pool]
            target_oid = None
            for i in range(256):
                oid = f"fullprobe{i}"
                pg = c.osdmap.object_to_pg(p, oid)
                if victim in c.osdmap.pg_to_acting(p, pg):
                    target_oid = oid
                    break
            if target_oid is None:
                failures.append("no PG maps onto the full OSD?")
            else:
                t0 = _time.monotonic()
                try:
                    await c.put(pool, target_oid, b"y" * 2000)
                    failures.append("write into a FULL acting set "
                                    "succeeded")
                except RadosError as e:
                    if e.code != -_errno.ENOSPC:
                        failures.append(
                            f"write failed untyped (code {e.code}, "
                            f"want ENOSPC): {e}")
                    elif _time.monotonic() - t0 > 3.0:
                        failures.append(
                            "ENOSPC took the slow retry path "
                            f"({_time.monotonic() - t0:.1f}s): not "
                            "fail-fast")
                # reads + deletes still served at FULL
                await verify_acked(c, pool, acked, failures, "full")
                await c.delete(pool, "o0")
                del acked["o0"]
                try:
                    await c.get(pool, "o0")
                    failures.append("deleted o0 still readable")
                except RadosError:
                    pass
            # the drain: injection cleared = utilization dropped
            cluster.conf["osd_debug_inject_full"] = ""
            await wait_for(lambda: state_is(""), args.full_seconds,
                           "full state to auto-clear", failures)

            async def no_full_checks():
                h = await c.get_health()
                checks = h.get("checks") or {}
                return not ({"OSD_FULL", "POOL_FULL", "OSD_NEARFULL"}
                            & set(checks))

            await wait_for(no_full_checks, args.full_seconds,
                           "fullness health checks to clear", failures)
            if target_oid is not None:
                blob = _os.urandom(3000)
                await c.put(pool, target_oid, blob)  # writes resume
                acked[target_oid] = blob
            await verify_acked(c, pool, acked, failures, "cleared")
            await c.stop()
        finally:
            cluster.conf["osd_debug_inject_full"] = ""
            await cluster.stop()

    async def leg_capacity(failures) -> None:
        # one OSD, one replica, a REAL 1 MiB ceiling: the failsafe must
        # refuse before the store bursts, deletes must drain it
        cap = 1 << 20
        conf = {"osd_auto_repair": False, "osd_heartbeat_interval": 0.1,
                "osd_store_capacity_bytes": cap,
                "client_op_timeout": 5.0, "client_op_deadline": 6.0}
        cluster = Cluster(n_osds=1, conf=conf)
        await cluster.start()
        try:
            c = await cluster.client()
            pool = await c.create_pool("cap", pool_type="replicated",
                                       profile={"size": "1"}, pg_num=8)
            acked = {}
            blocked = None
            for i in range(64):
                oid = f"c{i}"
                blob = _os.urandom(48 << 10)
                try:
                    await c.put(pool, oid, blob)
                    acked[oid] = blob
                except RadosError as e:
                    blocked = e
                    break
            if blocked is None:
                failures.append(
                    f"64 x 48KiB writes into a {cap}-byte store never "
                    f"hit the failsafe")
            elif blocked.code != -_errno.ENOSPC:
                failures.append(f"failsafe refusal untyped "
                                f"(code {blocked.code}): {blocked}")
            osd = next(iter(cluster.osds.values()))
            st = osd.store.statfs()
            if st["used"] > int(cap * 0.98):
                failures.append(f"store burst past the failsafe: "
                                f"used {st['used']} of {cap}")
            await verify_acked(c, pool, acked, failures, "capacity-full")
            # the ONLY way out: delete (exempt from every gate)
            for oid in list(acked)[: len(acked) * 2 // 3]:
                await c.delete(pool, oid)
                del acked[oid]

            async def can_write():
                try:
                    await c.put(pool, "after-drain", b"z" * 4096)
                    return True
                except RadosError:
                    return False

            if await wait_for(can_write, args.full_seconds,
                              "writes to resume after the drain",
                              failures):
                acked["after-drain"] = b"z" * 4096
            await verify_acked(c, pool, acked, failures, "drained")
            await c.stop()
        finally:
            await cluster.stop()

    async def leg_backfillfull(failures) -> None:
        conf = {"osd_auto_repair": True, "osd_heartbeat_interval": 0.1,
                "mon_osd_report_grace": 1.0,
                "osd_backfill_toofull_retry": 0.3,
                "osd_repair_delay": 0.1,
                "client_op_timeout": 5.0, "client_op_deadline": 6.0}
        cluster = Cluster(n_osds=max(4, args.full_osds), conf=conf)
        await cluster.start()
        try:
            c = await cluster.client()
            pool = await c.create_pool("bf", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            acked = {}
            for i in range(8):
                blob = _os.urandom(40_000 + 531 * i)
                await c.put(pool, f"b{i}", blob)
                acked[f"b{i}"] = blob
            ids = sorted(cluster.osds)
            target, dead = ids[0], ids[-1]
            cluster.conf["osd_debug_inject_full"] = f"{target}:0.92"

            async def target_backfillfull():
                h = await c.get_health()
                util = h.get("osd_utilization") or {}
                return (util.get(target)
                        or {}).get("state") == "backfillfull"

            await wait_for(target_backfillfull, args.full_seconds,
                           "backfillfull state", failures)
            # force backfill whose reservations land on the injected OSD
            await cluster.kill_osd(dead)

            async def parked():
                h = await c.get_health(detail=True)
                return "PG_BACKFILL_FULL" in (h.get("checks") or {})

            await wait_for(parked, args.full_seconds,
                           "PG_BACKFILL_FULL (backfill_toofull park)",
                           failures)
            # the target frees space -> the parked reservation retries
            # through and backfill completes
            cluster.conf["osd_debug_inject_full"] = ""

            async def resumed():
                h = await c.get_health(detail=True)
                checks = set(h.get("checks") or {})
                return not ({"PG_BACKFILL_FULL", "OSD_BACKFILLFULL"}
                            & checks)

            await wait_for(resumed, max(args.full_seconds, 15.0),
                           "backfill to resume after the target freed "
                           "space", failures)
            await verify_acked(c, pool, acked, failures, "backfilled")
            await c.stop()
        finally:
            cluster.conf["osd_debug_inject_full"] = ""
            await cluster.stop()

    async def go() -> int:
        failures: list = []
        for name, leg in (("injected-ladder", leg_injected),
                          ("real-capacity", leg_capacity),
                          ("backfillfull", leg_backfillfull)):
            t0 = _time.monotonic()
            try:
                await leg(failures)
            except Exception as e:
                import traceback

                traceback.print_exc()
                failures.append(f"[{name}] leg crashed: "
                                f"{type(e).__name__}: {e}")
            print(f"full: leg {name} done in "
                  f"{_time.monotonic() - t0:.1f}s "
                  f"({len(failures)} cumulative failures)")
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_rebalance(args) -> int:
    """Elastic-membership coexistence gate (CI), the acceptance bar of
    the r18 plane, runnable as one FAILING command:

        python -m ceph_tpu.tools.non_regression --rebalance

    Two legs:

    1. COEXISTENCE CYCLE: an `osd out` -> backfill-drain -> `osd in` ->
       refill -> `osd reweight` -> crush bucket-move (a host bucket
       appears and the victim migrates into it, mid-traffic, remap
       converging to zero degraded PGs) cycle runs while a RESERVED tenant
       (qos_class:gold) and a best-effort tenant drive verified
       read/write traffic AND pool-wide deep scrub fans out — the
       scrub + rebalance + client coexistence the background dmClock
       classes exist for.  Fails unless: the cycle converges (the out
       OSD drains to zero shards, refills after `in`), the reserved
       tenant has ZERO acked-op failures and every read was
       byte-identical, all data is byte-identical after convergence,
       the sweeps were CLASSED (rebalance/scrub dmClock enqueues moved),
       data actually moved, no PG_INCONSISTENT is left raised, and the
       reserved tenant's p99 during the cycle stays bounded:
       <= max(2x its solo p99, 1.5x the best-effort p99 of the SAME
       window, 250ms).  The best-effort and absolute terms absorb
       1-2-core CI hosts where process-wide CPU contention inflates
       every op (one event loop carries the whole cluster) — a real
       throttling regression shows gold >> best-effort in the same
       window and blows past all three terms.

    2. BACKFILLFULL PARK: the same out-drain aimed at a target past its
       backfillfull ratio parks (PG_BACKFILL_FULL raises) instead of
       stampeding the full disk, then resumes and completes when the
       target frees space — rebalance rides the r15 fullness gates.
    """
    import asyncio
    import os as _os
    import time as _time

    from ceph_tpu.rados.vstart import Cluster
    from ceph_tpu.tools.traffic import TenantClass, TrafficHarness

    async def wait_for(pred, seconds, what, failures):
        deadline = _time.monotonic() + seconds
        while _time.monotonic() < deadline:
            r = pred()
            if asyncio.iscoroutine(r):
                r = await r
            if r:
                return True
            await asyncio.sleep(0.1)
        failures.append(f"timed out waiting for {what}")
        return False

    def shards_on(osd, pool):
        return sum(1 for (p, _o, _s) in osd.store._data if p == pool)

    async def leg_coexistence(failures) -> None:
        conf = {"osd_op_queue": "mclock",
                "osd_mclock_profile": "balanced",
                "osd_auto_repair": True,
                "osd_heartbeat_interval": 0.1,
                "osd_repair_delay": 0.1,
                "osd_recovery_retry": 0.3,
                "mon_osd_report_grace": 2.0,
                "client_op_timeout": 30.0,
                "client_op_deadline": 60.0}
        cluster = Cluster(n_osds=max(4, args.rebalance_osds), conf=conf)
        await cluster.start()
        try:
            c0 = await cluster.client()
            pool = await c0.create_pool("rebal", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            await c0.pool_set(pool, "qos_class:gold", "100:20:0:0.5")
            c_gold = await cluster.client()
            c_be = await cluster.client()
            gold = TenantClass("gold", c_gold, tenants=1, workers=4,
                               rate=40.0)
            be = TenantClass("", c_be, tenants=8, workers=2, rate=20.0)
            h = TrafficHarness([gold, be], pool, n_objects=24,
                               obj_size=24 << 10, verify=True)
            await h.preload()
            victim_id = sorted(cluster.osds)[0]
            victim = cluster.osds[victim_id]
            await wait_for(lambda: shards_on(victim, pool) > 0, 10.0,
                           "the victim to hold shards", failures)
            shards_before = shards_on(victim, pool)

            solo = await h.run_phase("solo", 3.0, 0.25, classes=[gold])
            solo_p99 = solo.summary().get("gold", {}).get(
                "get", {}).get("p99_us", 0.0)

            moved0 = sum(o.perf.get("rebalance_bytes_moved")
                         for o in cluster.osds.values())
            scrub_stats = {"scrubbed": 0, "errors": 0}
            cycle_done = asyncio.Event()

            async def scrub_loop():
                # pool-wide deep scrub fanning out CONCURRENTLY with the
                # rebalance and the client traffic — the coexistence
                # under test
                while not cycle_done.is_set():
                    try:
                        res = await c0.deep_scrub(pool)
                        scrub_stats["scrubbed"] += res.get("scrubbed", 0)
                        scrub_stats["errors"] += res.get("errors", 0)
                    except Exception:
                        pass
                    await asyncio.sleep(0.2)

            async def cycle():
                try:
                    await c0.osd_out(victim_id)
                    await wait_for(
                        lambda: shards_on(victim, pool) == 0,
                        args.rebalance_seconds,
                        "the out OSD to drain", failures)
                    await c0.osd_in(victim_id)
                    await wait_for(
                        lambda: shards_on(victim, pool)
                        >= max(1, shards_before // 2),
                        args.rebalance_seconds,
                        "the re-added OSD to refill", failures)
                    await c0.osd_reweight(victim_id, 0.5)
                    await asyncio.sleep(0.5)  # remap settles under load
                    await c0.osd_reweight(victim_id, 1.0)
                    # bucket-move leg: runtime crush surgery mid-traffic
                    # — a host bucket appears and the victim migrates
                    # into it, the remap drains/refills through the same
                    # recovery machinery, still under the reserved
                    # tenant's zero-failure bar
                    await c0.osd_crush_op("add-bucket", "rebal-host",
                                          bucket_type="host")
                    await c0.osd_crush_op("move", f"osd.{victim_id}",
                                          dest="rebal-host")

                    async def move_clean():
                        # converged AND re-verified: a scrub racing the
                        # remap can transiently flag (and auto-repair)
                        # mid-backfill shards — hold the cycle open
                        # until a clean scrub clears the check
                        h = await c0.get_health()
                        checks = h.get("checks") or {}
                        return ("PG_DEGRADED" not in checks
                                and "PG_INCONSISTENT" not in checks)
                    await wait_for(move_clean, args.rebalance_seconds,
                                   "the bucket-move remap to converge "
                                   "and re-verify clean",
                                   failures)
                finally:
                    cycle_done.set()

            loop = asyncio.get_running_loop()
            scrub_task = loop.create_task(scrub_loop())
            cycle_task = loop.create_task(cycle())
            # the during-cycle traffic window: runs at least as long as
            # the cycle itself (phases repeat until the cycle finishes;
            # the FIRST phase overlaps the drain and carries the bound)
            during = await h.run_phase("rebalance", 4.0, 0.25)
            phases = [during]
            while not cycle_task.done():
                phases.append(await h.run_phase("rebalance-tail", 2.0,
                                                0.25))
            await cycle_task
            await scrub_task
            moved = sum(o.perf.get("rebalance_bytes_moved")
                        for o in cluster.osds.values()) - moved0

            dur_s = during.summary()
            gold_p99 = dur_s.get("gold", {}).get("get", {}).get(
                "p99_us", 0.0)
            be_p99 = dur_s.get("default", {}).get("get", {}).get(
                "p99_us", 0.0)
            gold_fail = (solo.summary().get("gold", {}).get("failures", 0)
                         + sum(ph.summary().get("gold", {}).get(
                             "failures", 0) for ph in phases))
            if gold_fail:
                failures.append(f"reserved tenant had {gold_fail} "
                                "acked-op failures during the cycle "
                                "(must be 0)")
            if moved <= 0:
                failures.append("no rebalance bytes were moved "
                                "(rebalance_bytes_moved stayed 0)")
            classed = sum(o.sched_perf.get("enqueue_rebalance")
                          for o in cluster.osds.values())
            scrub_classed = sum(o.sched_perf.get("enqueue_scrub")
                                for o in cluster.osds.values())
            if classed <= 0:
                failures.append("rebalance sweeps were never CLASSED "
                                "(enqueue_rebalance stayed 0)")
            if scrub_classed <= 0:
                failures.append("scrub sweeps were never CLASSED "
                                "(enqueue_scrub stayed 0)")
            if scrub_stats["scrubbed"] <= 0:
                failures.append("deep scrub never ran during the cycle")
            bound = max(2.0 * solo_p99, 1.5 * be_p99, 250_000.0)
            if not solo_p99 or not gold_p99:
                failures.append("reserved tenant percentiles missing "
                                f"(solo={solo_p99}, during={gold_p99})")
            elif gold_p99 > bound:
                failures.append(
                    f"reserved get p99 unbounded during rebalance: "
                    f"{gold_p99:.0f}us > max(2x solo {solo_p99:.0f}us, "
                    f"1.5x best-effort {be_p99:.0f}us, 250ms)")
            # convergence: every byte identical to the harness's
            # deterministic expectation
            for oid, want in h.blobs.items():
                try:
                    got = await c0.get(pool, oid)
                except Exception as e:
                    failures.append(f"{oid} unreadable after "
                                    f"convergence: {e}")
                    continue
                if bytes(got) != want:
                    failures.append(f"{oid} NOT byte-identical after "
                                    "convergence")
            h2 = await c0.get_health(detail=True)
            if "PG_INCONSISTENT" in (h2.get("checks") or {}):
                failures.append("PG_INCONSISTENT left raised after the "
                                "cycle (scrub found lasting damage)")
            window_s = during.seconds or 1.0
            print(f"rebalance: moved {moved / 1e6:.2f} MB "
                  f"({moved / window_s / 1e6:.2f} MB/s over the "
                  f"{window_s:.1f}s window), gold p99 solo "
                  f"{solo_p99:.0f}us -> during {gold_p99:.0f}us "
                  f"(best-effort {be_p99:.0f}us), rebalance enqueues "
                  f"{classed}, scrub enqueues {scrub_classed}, scrubbed "
                  f"{scrub_stats['scrubbed']} objects, "
                  f"{len(failures)} failures")
            for c in (c0, c_gold, c_be):
                await c.stop()
        finally:
            await cluster.stop()

    async def leg_backfillfull(failures) -> None:
        conf = {"osd_op_queue": "mclock",
                "osd_auto_repair": True,
                "osd_heartbeat_interval": 0.1,
                "osd_repair_delay": 0.1,
                "osd_recovery_retry": 0.3,
                "osd_backfill_toofull_retry": 0.3,
                "mon_osd_report_grace": 2.0,
                "client_op_timeout": 10.0, "client_op_deadline": 20.0}
        cluster = Cluster(n_osds=max(4, args.rebalance_osds), conf=conf)
        await cluster.start()
        try:
            c = await cluster.client()
            pool = await c.create_pool("rebalbf", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            acked = {}
            for i in range(8):
                blob = _os.urandom(40_000 + 531 * i)
                await c.put(pool, f"b{i}", blob)
                acked[f"b{i}"] = blob
            ids = sorted(cluster.osds)
            victim_id, target = ids[0], ids[1]
            victim = cluster.osds[victim_id]
            await wait_for(lambda: shards_on(victim, pool) > 0, 10.0,
                           "the victim to hold shards", failures)
            # a rebalance target past its backfillfull ratio: the drain
            # must PARK, not stampede the full disk
            cluster.conf["osd_debug_inject_full"] = f"{target}:0.92"

            async def target_backfillfull():
                h = await c.get_health()
                util = h.get("osd_utilization") or {}
                return (util.get(target)
                        or {}).get("state") == "backfillfull"

            await wait_for(target_backfillfull, args.rebalance_seconds,
                           "backfillfull state", failures)
            await c.osd_out(victim_id)

            async def parked():
                h = await c.get_health(detail=True)
                return "PG_BACKFILL_FULL" in (h.get("checks") or {})

            await wait_for(parked, args.rebalance_seconds,
                           "PG_BACKFILL_FULL (rebalance parked at the "
                           "backfillfull target)", failures)
            # space frees -> the parked rebalance resumes and completes
            cluster.conf["osd_debug_inject_full"] = ""
            await wait_for(lambda: shards_on(victim, pool) == 0,
                           max(args.rebalance_seconds, 20.0),
                           "the drain to resume and complete after the "
                           "target freed space", failures)
            for oid, want in acked.items():
                got = await c.get(pool, oid)
                if bytes(got) != want:
                    failures.append(f"{oid} NOT byte-identical after "
                                    "the parked-then-resumed drain")
            print(f"rebalance-backfillfull: parked and resumed, "
                  f"{len(failures)} cumulative failures")
            await c.stop()
        finally:
            cluster.conf["osd_debug_inject_full"] = ""
            await cluster.stop()

    async def go() -> int:
        failures: list = []
        for name, leg in (("coexistence", leg_coexistence),
                          ("backfillfull-park", leg_backfillfull)):
            t0 = _time.monotonic()
            try:
                await leg(failures)
            except Exception as e:
                import traceback

                traceback.print_exc()
                failures.append(f"[{name}] leg crashed: "
                                f"{type(e).__name__}: {e}")
            print(f"rebalance: leg {name} done in "
                  f"{_time.monotonic() - t0:.1f}s "
                  f"({len(failures)} cumulative failures)")
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_lifecycle(args) -> int:
    """Node-lifecycle thrash gate (CI), the acceptance bar of the
    membership lifecycle plane, runnable as one FAILING command:

        python -m ceph_tpu.tools.non_regression --lifecycle

    One arc, every step verified, all of it under continuous verified
    client traffic:

      1. `osd crush add-bucket` a host, `osd crush move` an OSD into it
         — the remap converges to zero degraded PGs mid-traffic.
      2. Kill a DIFFERENT OSD.  With `noout` set the mon must NOT
         auto-out it (the freeze flag); after `osd unset noout` the
         auto-out fires on its own (mon_osd_down_out_interval).
      3. Recovery drains the dead member: acting sets rebuild full,
         `osd safe-to-destroy` flips green (it REFUSED while PGs still
         mapped to the victim or weren't fully recovered).
      4. `osd purge` removes the victim from map + crush; `osd tree`
         no longer shows it.
      5. Byte-identity sweep over every object; the traffic harness
         must report ZERO acked-op failures across the whole arc.
    """
    import asyncio
    import time as _time

    from ceph_tpu.rados.vstart import Cluster
    from ceph_tpu.tools.traffic import TenantClass, TrafficHarness

    async def wait_for(pred, seconds, what, failures):
        deadline = _time.monotonic() + seconds
        while _time.monotonic() < deadline:
            r = pred()
            if asyncio.iscoroutine(r):
                r = await r
            if r:
                return True
            await asyncio.sleep(0.1)
        failures.append(f"timed out waiting for {what}")
        return False

    async def go() -> int:
        failures: list = []
        conf = {"osd_auto_repair": True,
                "osd_heartbeat_interval": 0.1,
                "osd_repair_delay": 0.1,
                "osd_recovery_retry": 0.3,
                "mon_osd_report_grace": 1.5,
                "mon_osd_down_out_interval": 0.6,
                "mon_osd_min_in_ratio": 0.3,
                "client_op_timeout": 30.0,
                "client_op_deadline": 60.0}
        cluster = Cluster(n_osds=max(5, args.lifecycle_osds), conf=conf)
        await cluster.start()
        try:
            c = await cluster.client()
            pool = await c.create_pool("life", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            c_t = await cluster.client()
            traffic = TenantClass("", c_t, tenants=4, workers=2,
                                  rate=25.0)
            h = TrafficHarness([traffic], pool, n_objects=24,
                               obj_size=24 << 10, verify=True)
            await h.preload()
            ids = sorted(cluster.osds)
            moved_id, victim_id = ids[0], ids[1]

            arc_done = asyncio.Event()
            arc_failures: list = []

            async def arc():
                try:
                    # 1. crush surgery + convergence
                    await c.osd_crush_op("add-bucket", "life-host",
                                         bucket_type="host")
                    await c.osd_crush_op("move", f"osd.{moved_id}",
                                         dest="life-host")
                    if c.osdmap.crush.parent_of(moved_id) != \
                            c.osdmap.crush.bucket_by_name("life-host").id:
                        arc_failures.append(
                            "crush move did not re-parent the OSD")

                    async def clean():
                        hh = await c.get_health()
                        return "PG_DEGRADED" not in (hh.get("checks")
                                                     or {})
                    await wait_for(clean, args.lifecycle_seconds,
                                   "the bucket-move remap to converge",
                                   arc_failures)
                    # 2. kill under noout: the freeze flag must hold
                    await c.osd_set_flag("noout", True)
                    await cluster.kill_osd(victim_id)
                    await wait_for(
                        lambda: _refresh_not_up(c, victim_id),
                        args.lifecycle_seconds,
                        "the mon to mark the victim down", arc_failures)
                    await asyncio.sleep(1.5)  # > down_out_interval
                    await c.refresh_map()
                    if not c.osdmap.osds[victim_id].in_cluster:
                        arc_failures.append(
                            "auto-out fired UNDER noout (the freeze "
                            "flag must block it)")
                    # safe-to-destroy must refuse while PGs still map
                    # to (or are degraded by) the down victim
                    r = await c.osd_safe_to_destroy(victim_id)
                    if r.safe:
                        arc_failures.append(
                            "safe-to-destroy said SAFE while the "
                            "victim's PGs were still degraded")
                    # 3. unset -> auto-out fires on its own
                    await c.osd_set_flag("noout", False)

                    async def outed():
                        await c.refresh_map()
                        i = c.osdmap.osds[victim_id]
                        return (not i.up) and (not i.in_cluster)
                    await wait_for(outed, args.lifecycle_seconds,
                                   "auto-out after noout cleared",
                                   arc_failures)
                    # drain: recovery rebuilds full acting sets

                    async def std_green():
                        await c.refresh_map()
                        return (await c.osd_safe_to_destroy(
                            victim_id)).safe
                    await wait_for(std_green,
                                   max(args.lifecycle_seconds, 40.0),
                                   "safe-to-destroy to flip green",
                                   arc_failures)
                    # 4. purge: gone from map AND crush
                    await c.osd_purge(victim_id)
                    await c.refresh_map()
                    if victim_id in c.osdmap.osds:
                        arc_failures.append("victim still in the "
                                            "osdmap after purge")
                    if victim_id in c.osdmap.crush.devices():
                        arc_failures.append("victim still in the "
                                            "crush map after purge")
                finally:
                    arc_done.set()

            loop = asyncio.get_running_loop()
            arc_task = loop.create_task(arc())
            phases = [await h.run_phase("lifecycle", 4.0, 0.25)]
            while not arc_task.done():
                phases.append(await h.run_phase("lifecycle-tail", 2.0,
                                                0.25))
            await arc_task
            failures.extend(arc_failures)
            # 5. zero acked-op loss + byte identity
            lost = sum(ph.summary().get("default", {}).get(
                "failures", 0) for ph in phases)
            if lost:
                failures.append(f"{lost} acked-op failures during the "
                                f"lifecycle arc (must be 0)")
            for oid, want in h.blobs.items():
                try:
                    got = await c.get(pool, oid)
                except Exception as e:
                    failures.append(f"{oid} unreadable after the arc: "
                                    f"{e}")
                    continue
                if bytes(got) != want:
                    failures.append(f"{oid} NOT byte-identical after "
                                    "the lifecycle arc")
            auto_outs = cluster.mon.perf.get("auto_outs")
            if auto_outs < 1:
                failures.append("mon auto_outs counter never moved")
            print(f"lifecycle: arc complete, auto_outs {auto_outs}, "
                  f"crush_moves {cluster.mon.perf.get('crush_moves')}, "
                  f"predicate_queries "
                  f"{cluster.mon.perf.get('predicate_queries')}, "
                  f"{len(failures)} failures")
            for cl in (c, c_t):
                await cl.stop()
        finally:
            await cluster.stop()
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    async def _refresh_not_up(c, osd_id) -> bool:
        await c.refresh_map()
        return not c.osdmap.osds[osd_id].up

    return asyncio.run(go())


def run_device_parity_child(args) -> int:
    """ONE slab arm's writeback lifecycle (the arm is whatever
    CEPH_TPU_DEVICE_SLAB says when the store builds): deterministic
    puts under cache_mode=writeback -> dirty pages -> agent flush ->
    evict -> cold re-read, byte identity checked at every read, and a
    ``DEVICE_PARITY {json}`` digest line for the parent to compare
    across arms."""
    import asyncio
    import hashlib
    import json
    import os as _os

    _os.environ["CEPH_TPU_FORCE_BATCH"] = "1"

    from ceph_tpu.rados.vstart import Cluster
    import ceph_tpu.rados.osd as osdmod

    async def go() -> int:
        conf = {"osd_auto_repair": False, "client_op_timeout": 60.0,
                "osd_heartbeat_interval": 0.1,
                "osd_hit_set_period": 0.5,
                "osd_min_read_recency_for_promote": 1,
                "osd_tier_agent_interval": 0.1,
                "osd_tier_target_max_bytes": 8 << 20,
                "osd_cache_target_full_ratio": 0.8,
                "osd_tier_flush_age": 0.3}
        cluster = Cluster(n_osds=3, conf=conf)
        await cluster.start()
        failures = []
        digests = {}
        snap = {}
        try:
            c = await cluster.client()
            pool = await c.create_pool("devp", profile={
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"})
            store = osdmod.shared_planar_store()
            if store is None or not hasattr(store, "dirty_items"):
                print("FAIL paged planar store did not engage",
                      file=sys.stderr)
                return 1
            await c.pool_set(pool, "cache_mode", "writeback")
            for o in cluster.osds.values():
                for _ in range(100):
                    p = (o.osdmap.pools.get(pool) if o.osdmap else None)
                    if p is not None and (getattr(p, "opts", {})
                                          or {}).get("cache_mode") \
                            == "writeback":
                        break
                    await asyncio.sleep(0.02)
            # DETERMINISTIC content: both arms must produce the same
            # bytes at every stage or the parent's digest compare fails
            rng = np.random.default_rng(20260806)
            blobs = {
                f"wb{i}": rng.integers(
                    0, 256, 120_000 + 4096 * i,
                    dtype=np.uint8).tobytes()
                for i in range(6)}
            saw_dirty = False
            for oid, data in blobs.items():
                await c.put(pool, oid, data)
                saw_dirty = saw_dirty or store.dirty_pages > 0
            if not saw_dirty:
                failures.append("writeback puts left no dirty pages")
            for oid, want in blobs.items():
                got = await c.get(pool, oid)
                if got != want:
                    failures.append(
                        f"dirty resident read mismatch on {oid}")
            for _ in range(200):
                if not store.has_dirty():
                    break
                await asyncio.sleep(0.05)
            if store.dirty_pages:
                failures.append(
                    f"dirty_pages {store.dirty_pages} never drained")
            for o in cluster.osds.values():
                if o._planar is not None:
                    for oid in blobs:
                        o._planar.drop(o._planar_key(pool, oid))
            for oid, want in blobs.items():
                got = await c.get(pool, oid, fadvise="dontneed")
                if got != want:
                    failures.append(
                        f"post-flush cold read mismatch on {oid}")
                digests[oid] = hashlib.sha256(got).hexdigest()
            if hasattr(store, "page_stats"):
                snap = store.page_stats()
            await c.stop()
        finally:
            await cluster.stop()
        print("DEVICE_PARITY " + json.dumps({
            "digests": digests,
            "device_arm": snap.get("device_arm", 0),
            "device_slabs": snap.get("device_slabs", 0),
            "h2d_installs": snap.get("h2d_installs", 0),
            "device_installs": snap.get("device_installs", 0),
            "d2h_gathers": snap.get("d2h_gathers", 0)}))
        for f in failures:
            print(f"FAIL {f}", file=sys.stderr)
        return 1 if failures else 0

    return asyncio.run(go())


def run_device_parity(args) -> int:
    """Slab-arm parity gate (CI), FAILING and runnable as one command:

        python -m ceph_tpu.tools.non_regression --device-parity

    Two children run the identical writeback cycle — one with
    CEPH_TPU_DEVICE_SLAB=1 (jitted device-arm kernels; on a CPU-only
    host they run on the jax-cpu backend, the exact device call
    structure) and one with =0 (the r20 host-numpy arm, the fallback
    when JAX has no device backend).  Every cold-re-read digest must
    match across arms, the device child must actually have engaged the
    device arm, and the host child must not have."""
    import json
    import subprocess

    results = {}
    for arm, env_val in (("device", "1"), ("host", "0")):
        env = dict(os.environ)
        env["CEPH_TPU_DEVICE_SLAB"] = env_val
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["CEPH_TPU_FORCE_BATCH"] = "1"
        proc = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.tools.non_regression",
             "--device-parity-child"],
            env=env, capture_output=True, text=True, timeout=600)
        sys.stderr.write(proc.stderr)
        line = next((ln for ln in proc.stdout.splitlines()
                     if ln.startswith("DEVICE_PARITY ")), None)
        if proc.returncode != 0 or line is None:
            print(f"FAIL {arm}-arm child rc={proc.returncode}",
                  file=sys.stderr)
            print(proc.stdout[-2000:], file=sys.stderr)
            return 1
        results[arm] = json.loads(line[len("DEVICE_PARITY "):])
    dev, host = results["device"], results["host"]
    failures = []
    if dev["digests"] != host["digests"]:
        diff = [oid for oid in dev["digests"]
                if dev["digests"].get(oid) != host["digests"].get(oid)]
        failures.append(
            f"device vs host arm cold-re-read digests diverge on "
            f"{diff} — the byte-identity gate")
    if not dev["device_arm"]:
        failures.append("CEPH_TPU_DEVICE_SLAB=1 child did not engage "
                        "the device arm")
    if host["device_arm"]:
        failures.append("CEPH_TPU_DEVICE_SLAB=0 child engaged the "
                        "device arm")
    if not (dev["h2d_installs"] + dev["device_installs"]):
        failures.append("device arm recorded no installs (kernels "
                        "never ran)")
    print(f"device parity: {len(dev['digests'])} writeback objects "
          f"byte-identical across slab arms; device arm "
          f"slabs={dev['device_slabs']} h2d={dev['h2d_installs']} "
          f"native={dev['device_installs']} d2h={dev['d2h_gathers']}")
    for f in failures:
        print(f"FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.slow_ops:
        return run_slow_ops(args)
    if args.crash:
        return run_crash(args)
    if args.qos:
        return run_qos(args)
    if args.device_parity:
        return run_device_parity(args)
    if args.device_parity_child:
        return run_device_parity_child(args)
    if args.tier:
        return run_tier(args)
    if args.full:
        return run_full(args)
    if args.rebalance:
        return run_rebalance(args)
    if args.lifecycle:
        return run_lifecycle(args)
    if args.chaos:
        return run_chaos(args)
    if args.wire_floor:
        return run_wire_floor(args)
    if not args.create and not args.check:
        print("must specify either --check, or --create", file=sys.stderr)
        return 1
    try:
        if args.create:
            code = run_create(args)
            if code:
                return code
        if args.check:
            return run_check(args)
    except Exception as e:
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
