"""Reference-compatible erasure-code benchmark CLI.

Same flags and output protocol as the reference's
ceph_erasure_code_benchmark (reference
src/test/erasure-code/ceph_erasure_code_benchmark.cc:40-144): prints
"<seconds>\t<KB processed>" on stdout, where KB = iterations * size/1024.

    python -m ceph_tpu.tools.benchmark --plugin tpu -P k=8 -P m=3 \
        --size 1048576 --iterations 16 --workload encode

Workloads: encode (timed encode loop), decode (encode once, then timed
decode with random | --erased | exhaustive erasure generation).  Every
decode mode verifies recovered content: exhaustive checks inline
(ceph_erasure_code_benchmark.cc:202-316); random and --erased collect the
erasure signatures the timed loop exercised and re-decode each distinct
one AFTER the loop (outside the timed window), so the CLI cannot report
a fast-but-wrong decode.
"""

from __future__ import annotations

import argparse
import itertools
import random
import sys
import time

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="erasure code benchmark")
    p.add_argument("--verbose", "-v", action="store_true")
    p.add_argument("--size", "-s", type=int, default=1024 * 1024,
                   help="size of the buffer to be encoded")
    p.add_argument("--iterations", "-i", type=int, default=1,
                   help="number of encode/decode runs")
    p.add_argument("--plugin", "-p", default="jerasure",
                   help="erasure code plugin name")
    p.add_argument("--workload", "-w", default="encode",
                   choices=("encode", "decode"))
    p.add_argument("--erasures", "-e", type=int, default=1,
                   help="number of erasures when decoding")
    p.add_argument("--erased", type=int, action="append", default=None,
                   help="erased chunk (repeat for more)")
    p.add_argument("--erasures-generation", "-E", default="random",
                   choices=("random", "exhaustive"))
    p.add_argument("--parameter", "-P", action="append", default=[],
                   help="add a parameter to the erasure code profile (k=v)")
    p.add_argument("--directory", default="",
                   help="plugin directory (ec_<name>.py files)")
    p.add_argument("--perf-dump", action="store_true",
                   help="after the run, print the gf2_sched/ec_plugin "
                        "perf counter snapshot as JSON on stderr (stdout "
                        "keeps the reference '<seconds>\\t<KB>' protocol)")
    return p.parse_args(argv)


def perf_dump_json() -> str:
    """The EC data-plane counter sets this CLI can exercise, as one JSON
    object: `gf2_sched` (schedule-cache hit/miss/compile/CSE) and
    `ec_plugin` (device dispatches vs CPU fallbacks through the tpu
    plugin seams).  Used with --perf-dump so BENCH-style harnesses can
    snapshot the breakdown without an admin socket."""
    import json

    sets = {}
    try:
        from ceph_tpu.ops.gf2 import SCHED_PERF

        sets["gf2_sched"] = SCHED_PERF.dump()
    except Exception:
        pass
    try:
        from ceph_tpu.ec.plugins.tpu import PLUGIN_PERF

        sets["ec_plugin"] = PLUGIN_PERF.dump()
    except Exception:
        pass
    return json.dumps(sets)


def build_profile(args):
    from ceph_tpu.tools import parse_parameters

    profile = {"plugin": args.plugin}
    profile.update(parse_parameters(args.parameter))
    return profile


def make_codec(args, profile):
    from ceph_tpu.ec.registry import registry

    return registry.factory(args.plugin, args.directory, dict(profile))


def bench_encode(codec, args) -> int:
    n = codec.get_chunk_count()
    data = b"X" * args.size
    want = set(range(n))
    begin = time.perf_counter()
    for _ in range(args.iterations):
        codec.encode(want, data)
    elapsed = time.perf_counter() - begin
    print(f"{elapsed:f}\t{args.iterations * (args.size // 1024)}")
    return 0


def decode_exhaustive(codec, encoded, erasures: int) -> int:
    """All erasure combinations up to `erasures` over the chunks present in
    `encoded` (chunks pre-erased via --erased are simply never available),
    verifying content (reference decode_erasures recursion,
    ceph_erasure_code_benchmark.cc:202-249)."""
    present = sorted(encoded)
    chunk_size = len(encoded[present[0]])
    for combo in itertools.combinations(present, erasures):
        available = {c: b for c, b in encoded.items() if c not in combo}
        decoded = codec.decode(set(combo), available, chunk_size)
        for c in combo:
            if not np.array_equal(decoded[c], encoded[c]):
                print(f"chunk {c} content and recovered content are different",
                      file=sys.stderr)
                return 1
    return 0


#: post-loop verification re-decodes at most this many distinct erasure
#: signatures (random mode can touch many over a long run; the content
#: check must stay O(signatures), not O(iterations))
VERIFY_SIGNATURE_CAP = 64


def verify_signatures(codec, encoded_full, signatures, chunk_size) -> int:
    """Re-decode each erasure signature outside the timed window and
    compare recovered content against the originally encoded chunks —
    the content check the reference only performs in exhaustive mode,
    applied to the random/--erased workloads' signature set."""
    for combo in signatures:
        available = {c: b for c, b in encoded_full.items() if c not in combo}
        decoded = codec.decode(set(combo), available, chunk_size)
        for c in combo:
            if not np.array_equal(decoded[c], encoded_full[c]):
                print(f"chunk {c} content and recovered content are different",
                      file=sys.stderr)
                return 1
    return 0


def bench_decode(codec, args) -> int:
    n = codec.get_chunk_count()
    data = b"X" * args.size
    encoded = codec.encode(set(range(n)), data)
    chunk_size = len(next(iter(encoded.values())))
    want = set(range(n))
    erased = args.erased or []
    encoded_full = dict(encoded)  # pre-erasure originals for verification
    if erased:
        for c in erased:
            encoded.pop(c, None)

    seen_signatures = set()
    begin = time.perf_counter()
    for _ in range(args.iterations):
        if args.erasures_generation == "exhaustive":
            code = decode_exhaustive(codec, encoded, args.erasures)
            if code:
                return code
        elif erased:
            codec.decode(want, encoded, chunk_size)
            seen_signatures.add(tuple(sorted(erased)))
        else:
            chunks = dict(encoded)
            for _ in range(args.erasures):
                while True:
                    erasure = random.randrange(n)
                    if erasure in chunks:
                        break
                del chunks[erasure]
            seen_signatures.add(tuple(sorted(set(encoded) - set(chunks))))
            codec.decode(want, chunks, chunk_size)
    elapsed = time.perf_counter() - begin
    # content check (outside the timed window): every distinct signature
    # the loop decoded, capped so verification stays bounded
    code = verify_signatures(
        codec, encoded_full,
        sorted(seen_signatures)[:VERIFY_SIGNATURE_CAP], chunk_size)
    if code:
        return code
    print(f"{elapsed:f}\t{args.iterations * (args.size // 1024)}")
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    profile = build_profile(args)
    try:
        codec = make_codec(args, profile)
    except Exception as e:
        print(f"factory({args.plugin}) failed: {e}", file=sys.stderr)
        return 1
    try:
        if args.workload == "encode":
            code = bench_encode(codec, args)
        else:
            code = bench_decode(codec, args)
        if args.perf_dump:
            print(perf_dump_json(), file=sys.stderr)
        return code
    except Exception as e:
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
