"""ceph_tpu — a TPU-native distributed-storage framework with Ceph's capabilities.

Built from scratch on JAX/XLA/Pallas (compute path) + C++ (native runtime), not a
port of the reference's C/C++ design.  The flagship subsystem is erasure coding:
a ``plugin=tpu`` Reed-Solomon GF(2^8) backend whose parity math runs as a
bit-plane GF(2) matmul on the TPU MXU, registered through the same pluggable
codec-registry architecture the reference uses (see
/root/reference/src/erasure-code/ErasureCodePlugin.h:24-79).

Layout:
  ceph_tpu.ec        codec interface, registry, GF math, CPU codecs, tpu plugin
  ceph_tpu.ops       JAX/Pallas kernels (bit-plane GF matmul and friends)
  ceph_tpu.parallel  device mesh, shardings, distributed EC service
  ceph_tpu.rados     mini-RADOS: messenger, monitor, OSD, EC backend, stores
  ceph_tpu.utils     buffers, profiles, config, perf counters, logging
"""

__version__ = "0.1.0"

# Plugin ABI version handshake, mirroring the reference's __erasure_code_version
# check against CEPH_GIT_NICE_VER (ErasureCodePlugin.cc:120-178): a plugin built
# against a different version is refused with -EXDEV.
PLUGIN_ABI_VERSION = __version__
