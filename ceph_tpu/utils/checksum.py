"""Process-wide data checksum: hardware CRC32C when the native layer
builds (native/crc32c.cc, SSE4.2), zlib.crc32 otherwise.

The reference checksums every wire frame and BlueStore extent with
accelerated crc32c (reference src/common/crc32c.cc); checksum time was a
visible slice of the Python daemon tax (VERDICT r03 weak #1), so every
internal checksum site (messenger frames, shard crcs, HashInfo chains,
BlueStore extents, KV WAL records) resolves through this one seedable
function.  The algorithm choice is an internal format detail — all
readers and writers of a deployment run the same build."""

from __future__ import annotations

import zlib

_IMPL = None
_KIND = None


def _resolve() -> None:
    global _IMPL, _KIND
    try:
        from ceph_tpu.native import bridge

        bridge.crc32c(b"probe")
        _IMPL = bridge.crc32c
        _KIND = "crc32c"
    except Exception:
        import logging

        logging.getLogger("ceph_tpu.checksum").warning(
            "native crc32c unavailable; falling back to zlib.crc32 "
            "(peers negotiate per connection)")
        _IMPL = zlib.crc32
        _KIND = "zlib"


def checksum(data, seed: int = 0) -> int:
    if _IMPL is None:
        _resolve()
    return _IMPL(data, seed)


def checksum_kind() -> str:
    """Which algorithm this process resolved ("crc32c" | "zlib") — rides
    the messenger handshake so mismatched builds degrade instead of
    rejecting every frame.  Resolving may BUILD the native library
    (seconds of g++): daemons call this at startup, never on a hot
    path."""
    if _KIND is None:
        _resolve()
    return _KIND
