"""Process-wide data checksum: hardware CRC32C when the native layer
builds (native/crc32c.cc, SSE4.2), zlib.crc32 otherwise.

The reference checksums every wire frame and BlueStore extent with
accelerated crc32c (reference src/common/crc32c.cc); checksum time was a
visible slice of the Python daemon tax (VERDICT r03 weak #1), so every
internal checksum site (messenger frames, shard crcs, HashInfo chains,
BlueStore extents, KV WAL records) resolves through this one seedable
function.  The algorithm choice is an internal format detail — all
readers and writers of a deployment run the same build."""

from __future__ import annotations

import zlib

_IMPL = None
_KIND = None


def _resolve() -> None:
    global _IMPL, _KIND
    try:
        from ceph_tpu.native import bridge

        bridge.crc32c(b"probe")
        _IMPL = bridge.crc32c
        _KIND = "crc32c"
    except Exception:
        import logging

        logging.getLogger("ceph_tpu.checksum").warning(
            "native crc32c unavailable; falling back to zlib.crc32 "
            "(peers negotiate per connection)")
        _IMPL = zlib.crc32
        _KIND = "zlib"


def checksum(data, seed: int = 0) -> int:
    if _IMPL is None:
        _resolve()
    return _IMPL(data, seed)


def checksum_kind() -> str:
    """Which algorithm this process resolved ("crc32c" | "zlib") — rides
    the messenger handshake so mismatched builds degrade instead of
    rejecting every frame.  Resolving may BUILD the native library
    (seconds of g++): daemons call this at startup, never on a hot
    path."""
    if _KIND is None:
        _resolve()
    return _KIND


_PY_TABLE = None


def _crc32c_py(data, seed: int = 0) -> int:
    """Pure-Python CRC32C (Castagnoli) — recovery/scrub-time verification
    only (slow): lets a build whose native library is gone still VERIFY
    records a crc32c build wrote, so persisted state never reads as torn."""
    global _PY_TABLE
    if _PY_TABLE is None:
        t = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            t.append(c)
        _PY_TABLE = t
    crc = seed ^ 0xFFFFFFFF
    tbl = _PY_TABLE
    for b in bytes(data):
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def verify_any(data, want: int) -> bool:
    """True when `want` matches this data under ANY checksum a build of
    this framework may have written it with (current resolver, zlib,
    crc32c-by-table) — the accept-either discipline for persisted state
    and cross-build wire comparisons; an algorithm change must degrade,
    never masquerade as corruption or a torn tail."""
    want &= 0xFFFFFFFF
    if checksum(data) & 0xFFFFFFFF == want:
        return True
    if zlib.crc32(data) & 0xFFFFFFFF == want:
        return True
    if _KIND != "crc32c" and _crc32c_py(data) == want:
        return True
    return False
