"""Process-wide wirepath resolver: the native messenger hot loop
(native/wirepath.cc via the ctypes bridge) when the native layer builds,
the pure-Python arm otherwise.

r13's sharded reactor measured the honest limit this module exists to
move: under the GIL, frame crc, fragment memcpy and writev segment
assembly serialize every reactor thread, so the multi-reactor TCP arm
cannot beat the single-loop path.  The native wirepath batches that
per-byte work into single foreign calls — ctypes drops the GIL around
them — so a flush window's writev, a burst's crc verify, and a striped
blob's scatter each cost ONE released-GIL call instead of N interpreter
iterations (checksum.py's discipline, applied to the whole wire loop).

Resolution mirrors utils/checksum.py: probe once per process, fall back
silently (hosts without a C++ toolchain run the full suite on the
python arm), and expose ``kind()`` so BENCH records and /metrics report
which arm actually ran.  ``CEPH_TPU_WIREPATH=0`` forces the python arm
process-wide (the CI parity knob); the per-messenger config option
``ms_wirepath_native`` gates it per daemon.

Per-process arm resolution under the process-sharded reactor plane
(``ms_reactor_mode=process``): ReactorProcessWorker.start() resolves
the arm in the PARENT before forking, so every worker child inherits a
loaded, probed bridge (ctypes handles survive fork) and never pays —
or races — a g++ build of its own.  After the fork the cached
resolution is genuinely per-process state: each worker runs its own
copy of the native wirepath, its ``wirepath_kind`` counter slot
reporting which arm that process carries.

The native arm only engages when the process checksum resolver is
crc32c (checksum.checksum_kind() == "crc32c"): the wirepath's crc
entry points compute crc32c, and a zlib-resolved host must keep
byte-identical zlib frames.  In practice the two resolve together —
they live in the same .so.
"""

from __future__ import annotations

import os
from typing import Optional

_IMPL = None  # the bridge module when native resolved
_KIND: Optional[str] = None


def _resolve() -> None:
    global _IMPL, _KIND
    if os.environ.get("CEPH_TPU_WIREPATH", "") == "0":
        _IMPL, _KIND = None, "python"
        return
    try:
        from ceph_tpu.utils import checksum

        if checksum.checksum_kind() != "crc32c":
            _IMPL, _KIND = None, "python"
            return
        from ceph_tpu.native import bridge

        # probe every entry point against the python arm once: a stale
        # or miscompiled .so must degrade to python, never ship bytes
        if bridge.wirepath_kind() != "native":
            raise RuntimeError("wirepath symbols missing")
        probe = b"wirepath-probe-0123456789abcdef" * 8
        want = bridge.crc32c(probe)
        if bridge.wire_crc_batch([[probe[:31], probe[31:]]]) != [want]:
            raise RuntimeError("wire_crc_batch mismatch")
        out = bytearray(len(probe))
        if bridge.wire_gather([probe[:7], probe[7:]], out) != len(probe) \
                or bytes(out) != probe:
            raise RuntimeError("wire_gather mismatch")
        dst = bytearray(len(probe))
        if bridge.wire_copy_crc32c(probe, dst) != want \
                or bytes(dst) != probe:
            raise RuntimeError("wire_copy_crc32c mismatch")
        rc, _bad = bridge.wire_scatter(
            [probe[16:], probe[:16]], [16, 0], dst,
            want_crcs=[bridge.crc32c(probe[16:]),
                       bridge.crc32c(probe[:16])])
        if rc != 2 or bytes(dst) != probe:
            raise RuntimeError("wire_scatter mismatch")
        if bridge.wire_verify_regions(
                probe, [0, 16], [16, len(probe) - 16],
                [bridge.crc32c(probe[:16]),
                 bridge.crc32c(probe[16:])]) != -1:
            raise RuntimeError("wire_verify_regions mismatch")
        if bridge.wirepath_selftest() != 0:
            raise RuntimeError("wirepath selftest failed")
        # the PyDLL shim is REQUIRED for the native arm: the tx hot
        # loop's segment-list parsing lives there (hosts with g++ but
        # no Python headers run the python arm — one arm per process,
        # never a half-native mix)
        if not bridge.has_wirepy():
            raise RuntimeError("wirepy shim unavailable")
        if bridge.wirepy_crc_chain([probe[:5], probe[5:]]) != want:
            raise RuntimeError("wirepy_crc_chain mismatch")
        out2 = bytearray(len(probe))
        if bridge.wirepy_gather([probe[:9], probe[9:]], out2) \
                != len(probe) or bytes(out2) != probe:
            raise RuntimeError("wirepy_gather mismatch")
        if bridge.wirepy_verify_regions(
                probe, [0, 16], [16, len(probe) - 16],
                [bridge.crc32c(probe[:16]),
                 bridge.crc32c(probe[16:])]) != -1:
            raise RuntimeError("wirepy_verify_regions mismatch")
        d1, d2 = bytearray(16), bytearray(len(probe) - 16)
        if bridge.wirepy_scatter_from(probe, [16, 0], [d2, d1]) \
                != len(probe) or bytes(d1) != probe[:16] \
                or bytes(d2) != probe[16:]:
            raise RuntimeError("wirepy_scatter_from mismatch")
        _IMPL, _KIND = bridge, "native"
    except Exception:
        import logging

        logging.getLogger("ceph_tpu.wirepath").warning(
            "native wirepath unavailable; messenger runs the python arm")
        _IMPL, _KIND = None, "python"


def impl():
    """The bridge module when the native wirepath resolved, else None —
    messengers branch on this once per connection, never per byte.
    First call may BUILD the native library (seconds of g++): daemons
    resolve at construction, like checksum_kind()."""
    if _KIND is None:
        _resolve()
    return _IMPL


def kind() -> str:
    """"native" | "python" — the arm this process resolved (BENCH's
    ``wirepath_kind``, checksum.checksum_kind's sibling)."""
    if _KIND is None:
        _resolve()
    return _KIND  # type: ignore[return-value]


def _reset_for_tests() -> None:
    """Drop the cached resolution so tests can exercise the
    CEPH_TPU_WIREPATH knob without a subprocess."""
    global _IMPL, _KIND
    _IMPL, _KIND = None, None
