"""Hang-proof JAX backend discovery.

The TPU service boundary is a failure domain the in-process dlopen model
does not have (SURVEY.md §7 hard part 5): when the device tunnel wedges,
``jax.default_backend()`` can block forever inside PJRT client creation —
observed live in this environment — and the registry contract is that a
codec returns -errno, it never hangs (the reference even ships a
hanging-plugin test fixture, TestErasureCodePlugin.cc:31-76).

``probe_backend()`` resolves the backend in a daemon thread with a
timeout.  On timeout the thread is abandoned (it is wedged in native code
and cannot be cancelled) and the result is pinned to "unavailable" for the
life of the process; callers then take their CPU fallback path and never
touch jax again.  The probe runs once; subsequent calls return the cached
verdict.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_lock = threading.Lock()
_result: Optional[str] = None
_error: Optional[BaseException] = None

UNAVAILABLE = "unavailable"


def probe_error() -> Optional[BaseException]:
    """The exception that made probe_backend() return UNAVAILABLE, if the
    probe failed with an error rather than a timeout."""
    return _error


def probe_backend(timeout: Optional[float] = None) -> str:
    """Return jax's default backend name ("tpu", "cpu", ...) or
    "unavailable" if backend init fails or does not finish in time."""
    global _result
    with _lock:
        if _result is not None:
            return _result
        if timeout is None:
            timeout = float(os.environ.get("CEPH_TPU_PROBE_TIMEOUT", "30"))
        box = {}

        def _probe() -> None:
            try:
                import jax

                box["backend"] = jax.default_backend()
            except Exception as e:  # import or init failure
                box["error"] = e

        th = threading.Thread(target=_probe, daemon=True, name="jax-probe")
        th.start()
        th.join(timeout)
        global _error
        _error = box.get("error")
        _result = box.get("backend", UNAVAILABLE)
        return _result


def backend_available() -> bool:
    return probe_backend() != UNAVAILABLE


def scrub_accelerator_env(n_cpu_devices: Optional[int] = None) -> dict:
    """Copy of os.environ safe for a CPU-only child process.

    Setting JAX_PLATFORMS=cpu in the child is not enough on hosts whose
    sitecustomize (PYTHONPATH entries containing "axon_site") registers an
    accelerator PJRT plugin in every python process when PALLAS_AXON_* vars
    are present: the child would still initialize libtpu and collide with
    an accelerator-holding parent on /tmp/libtpu_lockfile.  Strip the
    plugin triggers, force the CPU platform, and optionally force a virtual
    CPU device count.
    """
    out = dict(os.environ)
    for var in list(out):
        if var.startswith(("PALLAS_AXON_", "AXON_")) or var in (
            "TPU_LIBRARY_PATH",
            "PJRT_DEVICE",
        ):
            out.pop(var, None)
    pypath = [
        p
        for p in out.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon_site" not in p
    ]
    if pypath:
        out["PYTHONPATH"] = os.pathsep.join(pypath)
    else:
        out.pop("PYTHONPATH", None)
    out["JAX_PLATFORMS"] = "cpu"
    if n_cpu_devices is not None:
        kept = [
            f
            for f in out.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        out["XLA_FLAGS"] = " ".join(
            kept + [f"--xla_force_host_platform_device_count={n_cpu_devices}"]
        )
    return out
