"""Hang-proof JAX backend discovery.

The TPU service boundary is a failure domain the in-process dlopen model
does not have (SURVEY.md §7 hard part 5): when the device tunnel wedges,
``jax.default_backend()`` can block forever inside PJRT client creation —
observed live in this environment — and the registry contract is that a
codec returns -errno, it never hangs (the reference even ships a
hanging-plugin test fixture, TestErasureCodePlugin.cc:31-76).

``probe_backend()`` resolves the backend in a daemon thread with a
timeout.  On timeout the thread is abandoned (it is wedged in native code
and cannot be cancelled) and the result is pinned to "unavailable" for the
life of the process; callers then take their CPU fallback path and never
touch jax again.  The probe runs once; subsequent calls return the cached
verdict.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_lock = threading.Lock()
_result: Optional[str] = None

UNAVAILABLE = "unavailable"


def probe_backend(timeout: Optional[float] = None) -> str:
    """Return jax's default backend name ("tpu", "cpu", ...) or
    "unavailable" if backend init fails or does not finish in time."""
    global _result
    with _lock:
        if _result is not None:
            return _result
        if timeout is None:
            timeout = float(os.environ.get("CEPH_TPU_PROBE_TIMEOUT", "30"))
        box = {}

        def _probe() -> None:
            try:
                import jax

                box["backend"] = jax.default_backend()
            except Exception as e:  # import or init failure
                box["error"] = e

        th = threading.Thread(target=_probe, daemon=True, name="jax-probe")
        th.start()
        th.join(timeout)
        _result = box.get("backend", UNAVAILABLE)
        return _result


def backend_available() -> bool:
    return probe_backend() != UNAVAILABLE
