"""Shared runtime utilities (device probing, misc glue)."""
